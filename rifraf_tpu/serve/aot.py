"""AOT executable persistence: near-free cold starts for the serve grid.

The persistent XLA compilation cache (engine.driver) removes the
COMPILE half of a cold start, but a fresh process still pays full
Python tracing + lowering for every bucket-grid program — tens of
seconds of host work before the first flush can run. This module
removes the other half: warmed programs are lowered once through
``jax.export``, serialized, and persisted in a machine-fingerprinted
cache directory; a cold process (or a PR-7 supervisor restart, or a
PR-11 post-quarantine probe) deserializes the StableHLO payload and
compiles it directly, skipping tracing entirely.

Wiring: the module-level program factories in
``parallel.sweep_sharded`` (and the whole-stage runners built by
``engine.device_loop.make_stage_runner`` for ``engine.realign``) route
their jitted callables through :func:`aot_program`. The returned
``_Program`` is a zero-overhead pass-through while no cache is active
(``_ACTIVE is None`` — the default path stays byte-identical); once a
cache is activated (``ServeConfig.aot_cache``, the serve CLI's
``--aot-cache``, or :func:`activate_from_env`), every call consults the
cache keyed on (program kind, static config, argument avals, jax
version, backend, fused-impl routing):

- HIT: ``jax.export.deserialize(payload).call`` wrapped in ``jax.jit``
  — compiled from the serialized module, no tracing of the original
  function;
- MISS: the original jitted callable runs, then the traced computation
  is exported and persisted (atomic write) best-effort. Export failures
  (e.g. Pallas custom calls without serialization guarantees) are
  counted, never raised — persistence must not take down serving.

Entries are machine-specific like the XLA cache
(utils.cachedir.machine_cache_dir), and the PR-8 stale-cache recovery
path (engine.driver.recover_stale_cache) clears this directory along
with the compilation cache: a loaded-but-unrunnable payload falls back
to the traced original on its first call, so a poisoned entry degrades
to a warm miss instead of an outage.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Callable, Dict, Optional, Tuple

from ..utils.cachedir import (
    atomic_write_bytes,
    clear_cache_dir,
    default_aot_cache_dir,
)

# the process-wide active cache: installed by activate(), consulted by
# every _Program call. Module-level like the persistent compilation
# cache — the executable set is shared by design (a serving bucket and
# an offline sweep chunk use the same programs).
_ACTIVE: Optional["AotCache"] = None
_LOCK = threading.Lock()


def _env_key() -> str:
    """Environment facts that change compiled programs but are not in
    the factories' static keys: the fused-step routing env gate and the
    x64 flag (both flip executables under an unchanged call shape)."""
    import jax

    return "|".join((
        jax.__version__,
        jax.default_backend(),
        os.environ.get("RIFRAF_TPU_FUSED_IMPL", ""),
        "x64" if jax.config.jax_enable_x64 else "x32",
    ))


def _avals_digest(kind: str, statics: tuple, args) -> str:
    """Stable entry key: program kind + static config + the argument
    avals (shape/dtype/weak-type over the flattened pytree — weak types
    matter: a weak f32 scalar and a committed one lower differently)."""
    import jax
    from jax.api_util import shaped_abstractify

    leaves, treedef = jax.tree_util.tree_flatten(args)
    parts = [kind, repr(statics), _env_key(), str(treedef)]
    for leaf in leaves:
        a = shaped_abstractify(leaf)
        parts.append(
            f"{tuple(a.shape)}:{a.dtype}:{int(getattr(a, 'weak_type', False))}"
        )
    return hashlib.sha256("\x1f".join(parts).encode()).hexdigest()[:32]


class AotCache:
    """One persisted-executable directory: load/export + counters.

    Layout: ``<dir>/<kind>/<digest>.jaxexp`` — one serialized
    ``jax.export.Exported`` per (statics, avals, environment) key, kind
    subdirectories so an operator can inspect which program family owns
    the bytes. Counters (``snapshot()``) feed ``health()`` and the
    bench cold-start report.
    """

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        # digest -> compiled callable (or None: load/entry known bad,
        # pinned to the traced original)
        self._loaded: Dict[str, Optional[Callable]] = {}
        self._exported: set = set()
        self.counters: Dict[str, int] = {
            "aot_loads": 0, "aot_exports": 0, "aot_misses": 0,
            "aot_load_errors": 0, "aot_export_errors": 0,
        }

    def _count(self, name: str) -> None:
        with self._lock:
            self.counters[name] += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"dir": self.path, **self.counters}

    def _entry_path(self, kind: str, digest: str) -> str:
        return os.path.join(self.path, kind, f"{digest}.jaxexp")

    def clear(self) -> int:
        """Drop every persisted entry (stale-cache recovery); in-memory
        compiled callables stay — they already run correctly."""
        with self._lock:
            self._exported.clear()
        return clear_cache_dir(self.path)

    # ---- the load/export protocol (called by _Program) ----

    def lookup(self, kind: str, digest: str) -> Optional[Callable]:
        """The compiled callable for an entry, loading it from disk on
        first sight. Returns None when the entry is absent (the caller
        exports) or known-bad (the caller runs the traced original —
        ``known_bad`` distinguishes the two)."""
        with self._lock:
            if digest in self._loaded:
                return self._loaded[digest]
        path = self._entry_path(kind, digest)
        if not os.path.exists(path):
            return None
        fn: Optional[Callable] = None
        try:
            import jax
            from jax import export as jax_export

            with open(path, "rb") as fh:
                exported = jax_export.deserialize(fh.read())
            fn = jax.jit(exported.call)
            self._count("aot_loads")
        except Exception:  # noqa: BLE001 — a bad payload = warm miss
            self._count("aot_load_errors")
        with self._lock:
            self._loaded[digest] = fn
            if fn is not None:
                self._exported.add(digest)
        return fn

    def known_bad(self, digest: str) -> bool:
        with self._lock:
            return self._loaded.get(digest, "absent") is None

    def discard(self, digest: str) -> None:
        """Pin an entry to the traced original after its loaded form
        failed at run time (a deserialized module the current runtime
        refuses — e.g. an unregistered custom call)."""
        self._count("aot_load_errors")
        with self._lock:
            self._loaded[digest] = None

    def export(self, kind: str, digest: str, jitted: Callable,
               args) -> Optional[Callable]:
        """Best-effort persist: lower ``jitted`` at the call's avals
        through jax.export, write the serialized module atomically, and
        return the jit of the EXPORTED call. The caller runs THAT form,
        so the one compile the warm process pays is the same compile a
        cold process replays out of the persistent XLA cache — the
        exported module and the original jit lower to different cache
        keys, and compiling only the original would leave every first
        cold start paying a full compile anyway. Never raises — a
        program that cannot serialize (Pallas custom calls, donation
        quirks) just stays trace-warmed (returns None)."""
        with self._lock:
            if digest in self._exported:
                return self._loaded.get(digest)
            self._exported.add(digest)
        try:
            import jax
            from jax import export as jax_export

            exported = jax_export.export(jitted)(*args)
            atomic_write_bytes(self._entry_path(kind, digest),
                               exported.serialize())
            fn = jax.jit(exported.call)
            self._count("aot_exports")
            with self._lock:
                self._loaded[digest] = fn
            return fn
        except Exception:  # noqa: BLE001 — persistence is optional
            self._count("aot_export_errors")
            with self._lock:
                self._loaded[digest] = None
            return None


class _Program:
    """A jitted program factory product with an AOT escape hatch.

    Transparent while no cache is active: ``__call__`` forwards to the
    original jitted callable (same object, same executables — the
    default path is untouched). With an active cache, calls route
    through the persisted-entry protocol. Instances live inside the
    factories' lru caches, so per-(statics) load state persists across
    calls exactly like the jitted wrappers they replace.
    """

    __slots__ = ("kind", "statics", "jitted", "_digests")

    def __init__(self, kind: str, statics: tuple, jitted: Callable):
        self.kind = kind
        self.statics = statics
        self.jitted = jitted
        # per-avals digest memo (tracing shaped_abstractify over big
        # pytrees is cheap but not free; call shapes per program are
        # few) — keyed by the active cache id so a swapped cache
        # re-resolves
        self._digests: Dict[Tuple[int, str], str] = {}

    def _digest(self, cache: AotCache, args) -> str:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(args)
        quick = (id(cache), str(treedef),
                 tuple((tuple(x.shape) if hasattr(x, "shape") else (),
                        str(getattr(x, "dtype", type(x).__name__)))
                       for x in leaves))
        key = (id(cache), hashlib.sha256(
            repr(quick).encode()).hexdigest())
        got = self._digests.get(key)
        if got is None:
            got = _avals_digest(self.kind, self.statics, args)
            self._digests[key] = got
        return got

    def __call__(self, *args):
        cache = _ACTIVE
        if cache is None:
            return self.jitted(*args)
        digest = self._digest(cache, args)
        fn = cache.lookup(self.kind, digest)
        if fn is None and not cache.known_bad(digest):
            cache._count("aot_misses")
            fn = cache.export(self.kind, digest, self.jitted, args)
        if fn is not None:
            try:
                return fn(*args)
            except Exception:  # noqa: BLE001 — degrade to a warm miss
                # the payload deserialized (or exported) but will not
                # run here (stale runtime, unregistered custom call):
                # pin this entry to the traced original and keep serving
                cache.discard(digest)
        return self.jitted(*args)


def aot_program(kind: str, statics: tuple,
                jitted: Callable) -> Callable:
    """Wrap a freshly built jitted program for the factories: returns a
    ``_Program`` that is a pass-through until a cache is activated."""
    return _Program(kind, statics, jitted)


# ---- activation ----


def active_cache() -> Optional[AotCache]:
    return _ACTIVE


def activate(path: str) -> AotCache:
    """Install (or reuse) the process-wide AOT cache at ``path``.
    Idempotent for a repeated path; a different path replaces the
    active cache (loaded executables of the old one are dropped with
    it)."""
    global _ACTIVE
    with _LOCK:
        if _ACTIVE is not None and _ACTIVE.path == str(path):
            return _ACTIVE
        cache = AotCache(path)
        _ACTIVE = cache
        return cache


def deactivate() -> None:
    """Remove the active cache: factories fall back to their traced
    originals (tests; the stale-cache recovery path keeps serving from
    memory but stops touching disk)."""
    global _ACTIVE
    with _LOCK:
        _ACTIVE = None


def resolve_aot_dir(setting: Optional[str]) -> Optional[str]:
    """Map a config/CLI setting to a cache dir or None (disabled).
    ``None`` follows the ``RIFRAF_TPU_AOT_CACHE`` env var (unset or
    empty = disabled; ``default`` = the fingerprinted default dir);
    ``"off"`` disables explicitly; anything else is the directory."""
    if setting is None:
        setting = os.environ.get("RIFRAF_TPU_AOT_CACHE", "")
    if not setting or setting == "off":
        return None
    if setting == "default":
        return default_aot_cache_dir()
    return str(setting)


def activate_from_env() -> Optional[AotCache]:
    """Env-gated activation (bench, offline sweeps): installs the cache
    named by ``RIFRAF_TPU_AOT_CACHE`` when set."""
    d = resolve_aot_dir(None)
    return activate(d) if d else None


def clear_aot_cache() -> int:
    """Stale-runtime recovery hook (engine.driver.recover_stale_cache):
    drop the active cache's persisted entries AND the default dir's (a
    process that never activated still must not leave poisoned entries
    for the next one). Never raises."""
    n = 0
    try:
        cache = _ACTIVE
        if cache is not None:
            n += cache.clear()
            if cache.path != default_aot_cache_dir():
                n += clear_cache_dir(default_aot_cache_dir())
        else:
            n += clear_cache_dir(default_aot_cache_dir())
    except Exception:  # noqa: BLE001 — recovery must never raise
        pass
    return n
