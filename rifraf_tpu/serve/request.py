"""Serving request/response types and the server configuration.

A request is one cluster of ``ReadScores`` (the same unit as one
``rifraf()`` call or one cluster of ``sweep_clusters_sharded``). The
server's scope matches the sharded sweep: the no-reference device-loop
configuration, bit-identical per request to
``rifraf(..., batch_size=0, batch_fixed=False, device_loop="on")`` with
the configured ``do_alignment_proposals`` (tests/test_serve.py).
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..engine.params import DEFAULT_SCORES
from ..models.errormodel import Scores
from ..models.sequences import ReadScores, make_read_scores
from ..utils.constants import CODON_LENGTH, decode_seq
from .errors import ServeError


@dataclass
class ServeConfig:
    """All serving tunables.

    Shape routing reuses the sweep scheduler's grid
    (``parallel.sweep_sharded.bucket_key``): requests are micro-batched
    per ``(Npad, Lpad, Tmax, K0)`` signature, so the executable set
    stays small and is SHARED with offline sweeps.
    """

    # --- admission / flush policy ---
    # bounded admission queue: submit() raises QueueFullError beyond this
    max_queue: int = 256
    # flush a bucket as soon as it holds this many requests (also the
    # cluster-axis padding ceiling of a micro-batch)
    max_batch: int = 16
    # ... or as soon as its pending requests fill the 128-lane vector
    # axis (post-packing lane demand >= lane_target): a big-cluster
    # bucket (say Npad=64) dispatches at 2 requests instead of waiting
    # out max_wait_ms for 14 more that would only add lane tiles. With
    # segment packing the demand counts pending READS (requests share a
    # lane block at read granularity); without it, whole Npad blocks.
    # 0 disables
    lane_target: int = 128
    # cross-request segment packing: small same-shape requests share one
    # lane block at read granularity (parallel.sweep_sharded segment
    # plans). None follows the RIFRAF_TPU_SEGMENT_PACK env gate; results
    # are bit-identical either way (tests/test_lane_packing.py)
    segment_pack: Optional[bool] = None
    # ... or when its oldest request has waited this long
    max_wait_ms: float = 20.0
    # ... or when any member's deadline is within this margin (the time
    # one dispatch+fetch is assumed to need; tune to your p95)
    deadline_margin_ms: float = 50.0

    # --- shape grid (must match offline sweeps to share executables) ---
    read_bucket: int = 8
    band_bucket: int = 16
    len_bucket: int = 64

    # --- graceful-degradation limits ---
    # beyond these the request still runs, but as a per-cluster
    # device-loop fallback (engine.device_loop via rifraf()) instead of
    # joining a micro-batch
    batch_max_reads: int = 64
    batch_max_len: int = 2048
    batch_max_band: int = 512
    # beyond these the request is rejected outright (OversizeError)
    max_reads: int = 4096
    max_len: int = 65536

    # --- robustness / supervision ---
    # deterministic fault injection: a serve.faults.FaultPlan, a spec
    # string (see serve/faults.py grammar), or None to follow the
    # RIFRAF_TPU_FAULTS env var (empty = no faults)
    faults: Optional[object] = None
    # supervisor thread: heartbeats the batcher/worker threads, restarts
    # a crashed worker, watches for stalls
    supervise: bool = True
    supervise_interval_s: float = 0.05
    # a worker busy on one burst for longer than this is counted as
    # stalled (the worker_stalls counter; the thread cannot be killed,
    # only observed — restart handles DEAD threads). The default sits
    # above a cold first-compile so an unwarmed server does not count
    # its own tracing as a stall
    stall_timeout_s: float = 120.0
    # crashed-worker restart cap + exponential backoff (backoff_s * 2^k
    # before restart k); past the cap the server declares itself
    # unhealthy, fails everything outstanding, and rejects new submits
    max_restarts: int = 3
    restart_backoff_s: float = 0.05
    # a crash after this long of clean running RESETS the restart
    # budget (and with it the exponential backoff): a worker that
    # crashes once an hour is a transient, not a crash loop, and must
    # neither wait minutes to restart nor creep toward the unhealthy
    # cap. Only an actual loop — crashes closer together than this —
    # accumulates
    restart_backoff_reset_s: float = 30.0
    # degradation ladder: per-request retry budget across the rungs
    # (segment-packed -> whole-block batch -> per-request fallback); 2
    # covers the full descent
    max_retries: int = 2
    # --- result integrity (all OFF by default: the f32 default path
    # with integrity off is bit-identical to the unguarded code) ---
    # on-device numerical sentinels: the executor requests the
    # want_guard= reduction and raises NumericalIntegrityError on
    # NaN/+Inf/sentinel-underflow in band tables, scores, or totals
    guard: bool = False
    # shadow verification: deterministically sample this fraction of
    # completed results (by content digest) and re-score them on the
    # independent oracle path (engine.integrity.oracle_rescore — the
    # alternate RIFRAF_TPU_FUSED_IMPL routing). A divergence beyond the
    # precision-harness tolerance is counted, attributed to the worker's
    # device on the quarantine scoreboard, and the ORACLE result is
    # returned instead of the bad answer (path="verified")
    verify_fraction: float = 0.0
    # suspect-device quarantine: guard trips + divergences per device
    # before it is evicted from the round-robin; it rejoins only after
    # passing the known-answer golden probe. 0 disables eviction.
    # Quarantine/probes are active only when guard or verify_fraction
    # enables the integrity layer
    quarantine_threshold: int = 2
    # min seconds between golden probes of a quarantined/restarted
    # worker (rate limit on the re-probe loop)
    probe_interval_s: float = 0.05

    # synchronous waits (submit_many, CLI drain) give up after this long
    # per request and report WaitTimeoutError instead of hanging on a
    # dead pipeline; requests with deadlines derive a tighter bound
    result_timeout_s: float = 300.0
    # close(timeout=None) drains with this deadline before resolving
    # abandoned futures with ServerClosedError; None = wait forever
    close_timeout_s: Optional[float] = 60.0

    # --- engine parameters (the device-loop configuration) ---
    max_iters: int = 100
    min_dist: int = 5 * CODON_LENGTH
    bandwidth_pvalue: float = 0.1
    do_alignment_proposals: bool = False
    # band-table storage precision ("f32" | "bf16") and bandwidth growth
    # policy ("double" | "adaptive") — see engine.params.RifrafParams.
    # Both change compiled executables and numeric results, so they are
    # part of the spool fingerprint: a --resume across a changed value
    # is refused instead of silently mixing precisions
    band_dtype: str = "f32"
    band_growth: str = "double"
    # streamed-input encoding ("f32" | "packed") — see
    # engine.params.RifrafParams.input_enc. The serving micro-batches
    # run XLA device programs (exact f32 inputs either way), but the
    # knob keys the compiled-program caches, flows into the fallback /
    # oracle-verify engines, and is part of the spool fingerprint: a
    # --resume across a changed value is refused. Both encodings can
    # coexist in one process — program caches key on the value
    input_enc: str = "f32"
    # speculative edit-set evaluation (0 | 1 | 2) — see
    # engine.params.RifrafParams.speculate_k. Results are bit-identical
    # to the serial hill-climb (a speculative round is accepted only
    # when the replayed greedy rule verifies it); the knob changes the
    # compiled stage programs and the journaled round provenance, so it
    # keys the program caches and folds into the spool fingerprint when
    # non-default. The extra segment lanes are counted as overhead in
    # ServerStats, keeping lane-occupancy comparable across settings
    speculate_k: int = 0
    # scores/bandwidth used by encode_cluster() and the singleton
    # fallback path; clusters submitted as ready-made ReadScores must
    # have been built with the SAME values or fallback results will not
    # be bit-identical to batched ones
    scores: Scores = DEFAULT_SCORES
    bandwidth: int = 3 * CODON_LENGTH
    # optional Mesh whose first axis shards the micro-batch cluster axis
    mesh: Optional[object] = None
    # device-parallel FLEET: this many worker threads share the flush
    # queue, each with its own ChunkExecutor pinned to one device
    # (round-robin over jax.devices()). The lru-cached program factories
    # and the fingerprinted persistent compilation cache are shared, so
    # the bucket grid warms once per fleet. Mutually exclusive with
    # ``mesh`` (shard ONE program over devices, or run one program PER
    # device — not both)
    n_workers: int = 1
    # --- elastic fleet (queue-driven autoscaling) ---
    # max_workers == 0 (default) disables: the fleet is the fixed
    # n_workers above. With max_workers > 0 the supervisor scales the
    # worker count between max(1, min_workers) and max_workers against
    # queue-depth and time-in-queue signals; scale-down is a graceful
    # drain (the chosen worker finishes its in-flight burst, requeues
    # nothing, resolves every future, then retires). Parked/quarantined
    # slots never count toward the target. Mutually exclusive with
    # ``mesh`` like n_workers
    min_workers: int = 0
    max_workers: int = 0
    # scale UP when pending work exceeds this many flushes per active
    # worker ...
    scale_up_depth: int = 2
    # ... or when the dispatch-time queue-wait EWMA exceeds this while
    # work is pending
    scale_up_wait_s: float = 1.0
    # scale DOWN one worker after the fleet has been fully idle (no
    # queued work, no busy worker) this long
    scale_down_idle_s: float = 2.0
    # min seconds between scale operations (one step per cooldown)
    scale_cooldown_s: float = 0.5

    # --- admission control (deadline-aware load shedding) ---
    # with shed on, submit() estimates the queue service time ahead of
    # a deadline-carrying request (outstanding requests x service-time
    # EWMA / active workers) and raises SheddedError — with a
    # retry-after hint — when the estimate exceeds the deadline budget:
    # doomed work is refused at the door instead of timing out in the
    # queue. Off by default (requests then ride the binary
    # QueueFullError backpressure only); requests without deadlines are
    # never shed
    shed: bool = False

    # --- AOT executable persistence (serve.aot) ---
    # persisted-executable cache dir: a cold process deserializes the
    # warmed bucket grid's exported programs instead of re-tracing.
    # None follows the RIFRAF_TPU_AOT_CACHE env var (unset/empty =
    # disabled), "off" disables, "default" uses the machine-
    # fingerprinted default dir, anything else is the directory itself.
    # Activation is process-wide (like the persistent XLA compilation
    # cache): offline sweeps in the same process share the entries
    aot_cache: Optional[str] = None

    # --- durability ---
    # write-ahead completion hook: called as journal(response) from the
    # worker AFTER the request's future resolves OK (never for
    # rejections — those are safe to recompute). The serve CLI points
    # this at a per-file io.journal.Journal so a killed spool run can
    # --resume past completed request ids. Exceptions from the hook are
    # swallowed + counted; durability must never take down serving
    journal: Optional[object] = None


def encode_cluster(
    seqs: Sequence,
    phreds: Optional[Sequence[np.ndarray]] = None,
    error_log_ps: Optional[Sequence[np.ndarray]] = None,
    config: Optional[ServeConfig] = None,
) -> List[ReadScores]:
    """Build a request cluster from raw sequences + quality scores using
    the server's configured scores/bandwidth (so batched and fallback
    paths agree). Accepts DNA strings or int8 code arrays."""
    from ..utils.constants import encode_seq
    from ..utils.phred import phred_to_log_p

    from ..engine.validate import validate_cluster

    config = config or ServeConfig()
    if error_log_ps is None and phreds is None:
        raise ValueError("provide phreds or error_log_ps")
    # typed validation BEFORE any encoding/device work: zero-length
    # reads, seq/qual mismatches, out-of-range phreds, non-ACGT bytes
    # raise InvalidInputError subclasses with record context here
    validate_cluster(seqs, phreds, error_log_ps, source="encode_cluster")
    if error_log_ps is None:
        error_log_ps = [phred_to_log_p(np.asarray(p, float)) for p in phreds]
    return [
        make_read_scores(
            encode_seq(s) if isinstance(s, str) else np.asarray(s, np.int8),
            lp, config.bandwidth, config.scores,
        )
        for s, lp in zip(seqs, error_log_ps)
    ]


@dataclass
class Request:
    """One admitted cluster plus its routing facts."""

    id: str
    cluster: List[ReadScores]
    info: object  # parallel.sweep_sharded._ClusterInfo
    key: Tuple[int, int, int, int]  # bucket_key routing signature
    t_submit: float  # perf_counter at admission
    deadline: Optional[float]  # absolute perf_counter time, or None
    future: Future = field(default_factory=Future)
    # degradation-ladder retry budget consumed so far (worker-owned)
    retries: int = 0
    # perf_counter when a worker first picked the request up (pack
    # time): queue-wait = t_dispatch - t_submit feeds the elastic
    # scale-up signal; service = resolve - t_dispatch feeds the
    # shed estimator
    t_dispatch: Optional[float] = None

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.perf_counter() if now is None else now) > self.deadline


@dataclass
class Response:
    """Terminal outcome of one request. ``ok`` is False for typed
    rejections; ``error`` then holds the ServeError instance."""

    id: str
    ok: bool
    consensus: Optional[np.ndarray] = None
    score: Optional[float] = None
    n_iters: int = 0
    converged: bool = False
    error: Optional[ServeError] = None
    latency_s: float = 0.0
    # "batched" (micro-batched sweep chunk), "fallback" (per-cluster
    # device loop), or "rejected"
    path: str = "batched"

    def to_json_dict(self) -> dict:
        """JSONL wire form (the rifraf-serve CLI response schema)."""
        if not self.ok:
            return {
                "id": self.id, "ok": False,
                "error": self.error.code if self.error else "serve_error",
                "message": str(self.error) if self.error else "",
                "latency_ms": round(self.latency_s * 1e3, 3),
            }
        return {
            "id": self.id, "ok": True,
            "consensus": decode_seq(self.consensus),
            "score": float(self.score),
            "n_iters": int(self.n_iters),
            "converged": bool(self.converged),
            "latency_ms": round(self.latency_s * 1e3, 3),
            "path": self.path,
        }
