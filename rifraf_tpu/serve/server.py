"""ConsensusServer: the online consensus service front-end.

Three threads cooperate:

- the CALLER thread runs ``submit()``: admission checks (empty /
  oversize / closed / queue-full) happen synchronously so typed errors
  reach the caller immediately — backpressure is an exception, never a
  block;
- the BATCHER thread drains the admission queue into the MicroBatcher
  and pushes due flushes (bucket-full / max-wait / deadline-risk) to
  the worker's flush queue;
- the WORKER thread (``worker.Worker.run_loop``) pipelines flushes
  through the shared ChunkExecutor with double-buffered dispatch.

``submit()`` returns a ``concurrent.futures.Future[Response]``;
``submit_many()`` is the synchronous batch convenience that rides the
backpressure signal instead of surfacing it.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from queue import Empty, Full, Queue
from typing import List, Optional, Sequence

from ..models.sequences import ReadScores
from .batcher import MicroBatcher
from .errors import (
    EmptyClusterError,
    OversizeError,
    QueueFullError,
    ServeError,
    ServerClosedError,
)
from .request import Request, Response, ServeConfig
from .stats import ServerStats
from .worker import STOP, Flush, Worker, respond_error

_SHUTDOWN = object()  # admission-queue shutdown sentinel


class ConsensusServer:
    """Online consensus with continuous micro-batching and deadlines."""

    def __init__(self, config: Optional[ServeConfig] = None,
                 stats: Optional[ServerStats] = None, start: bool = True):
        self.config = config or ServeConfig()
        self.stats = stats or ServerStats()
        self._admit_q: Queue = Queue(maxsize=self.config.max_queue)
        self._flush_q: Queue = Queue()
        self._batcher = MicroBatcher(self.config)
        self._worker = Worker(self.config, self.stats)
        self._ids = itertools.count()
        self._closed = False
        self._threads: List[threading.Thread] = []
        if start:
            self.start()

    # ---- lifecycle ----

    def start(self) -> "ConsensusServer":
        if self._threads:
            return self
        bt = threading.Thread(target=self._batch_loop, daemon=True,
                              name="rifraf-serve-batcher")
        wt = threading.Thread(target=self._worker.run_loop,
                              args=(self._flush_q,), daemon=True,
                              name="rifraf-serve-worker")
        self._threads = [bt, wt]
        bt.start()
        wt.start()
        return self

    def close(self, timeout: Optional[float] = None) -> None:
        """Drain pending work, then stop both threads. Requests already
        admitted still complete; submit() afterwards raises
        ServerClosedError."""
        if self._closed:
            return
        self._closed = True
        if not self._threads:
            return
        bt, wt = self._threads
        self._admit_q.put(_SHUTDOWN)
        bt.join(timeout)
        self._flush_q.put(STOP)
        wt.join(timeout)

    def __enter__(self) -> "ConsensusServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- admission (caller thread) ----

    def submit(self, cluster: Sequence[ReadScores], *,
               request_id: Optional[str] = None,
               deadline_ms: Optional[float] = None):
        """Admit one cluster; returns Future[Response].

        Raises synchronously: ServerClosedError, EmptyClusterError,
        OversizeError (hard shape limits), QueueFullError (bounded
        admission queue — the backpressure signal; back off and retry).
        """
        from ..parallel.sweep_sharded import bucket_key, cluster_info

        if self._closed:
            raise ServerClosedError("server is closed")
        if not cluster:
            raise EmptyClusterError("request carries no reads")
        cfg = self.config
        info = cluster_info(cluster)
        if info.n_reads > cfg.max_reads or info.max_len > cfg.max_len:
            raise OversizeError(
                f"cluster shape ({info.n_reads} reads, max len "
                f"{info.max_len}) exceeds hard limits "
                f"({cfg.max_reads} reads, len {cfg.max_len})"
            )
        now = time.perf_counter()
        req = Request(
            id=request_id if request_id is not None
            else f"r{next(self._ids)}",
            cluster=list(cluster),
            info=info,
            key=bucket_key(info, cfg.read_bucket, cfg.band_bucket,
                           cfg.len_bucket),
            t_submit=now,
            deadline=(now + deadline_ms / 1e3
                      if deadline_ms is not None else None),
        )
        oversize_for_batch = (
            info.n_reads > cfg.batch_max_reads
            or info.max_len > cfg.batch_max_len
            or info.entry_k > cfg.batch_max_band
        )
        kind = "fallback" if oversize_for_batch else "batch"
        try:
            self._admit_q.put_nowait((kind, req))
        except Full:
            self.stats.count("rejected_queue_full")
            raise QueueFullError(
                f"admission queue at capacity ({cfg.max_queue})"
            ) from None
        self.stats.count("submitted")
        return req.future

    # ---- batcher thread ----

    def _batch_loop(self) -> None:
        from .errors import DeadlineExceededError

        while True:
            timeout = self._batcher.next_due(time.perf_counter())
            try:
                item = self._admit_q.get(timeout=timeout)
            except Empty:
                item = None
            if item is _SHUTDOWN:
                # drain: everything already admitted still runs
                while True:
                    try:
                        kind, req = self._admit_q.get_nowait()
                    except Empty:
                        break
                    self._route(kind, req)
                for bucket in self._batcher.drain():
                    self._flush("batch", bucket, "flush_drain")
                return
            if item is not None:
                kind, req = item
                if req.expired():
                    respond_error(req, DeadlineExceededError(
                        f"request {req.id}: deadline passed in queue"
                    ), self.stats, "rejected_deadline")
                else:
                    self._route(kind, req)
            for bucket in self._batcher.due(time.perf_counter()):
                self._flush("batch", bucket, "flush_timer")

    def _route(self, kind: str, req: Request) -> None:
        if kind == "fallback":
            self._flush("fallback", [req], "flush_fallback")
            return
        full = self._batcher.add(req)
        if full is not None:
            self._flush("batch", full, "flush_full")

    def _flush(self, kind: str, requests: List[Request],
               counter: str) -> None:
        self.stats.count(counter)
        self._flush_q.put(Flush(kind, requests))

    # ---- warmup / observability ----

    def warmup(self, example_clusters: Sequence[Sequence[ReadScores]],
               batch_sizes: Sequence[int] = (1,)) -> int:
        """Pre-trace the bucket-grid executables before taking traffic.

        Groups the examples by routing signature and runs one synthetic
        micro-batch per (signature, padded batch size) through the
        ChunkExecutor — with the fingerprinted XLA compilation cache
        enabled, so a restarted server rehydrates from disk instead of
        recompiling. Returns the number of executables exercised.
        """
        from ..engine.driver import _enable_compilation_cache
        from ..parallel.sweep_sharded import bucket_key, cluster_info

        _enable_compilation_cache()
        cfg = self.config
        by_key = {}
        for c in example_clusters:
            info = cluster_info(c)
            key = bucket_key(info, cfg.read_bucket, cfg.band_bucket,
                             cfg.len_bucket)
            by_key.setdefault(key, (list(c), info))
        n_traced = 0
        with self.stats.timers.time("serve_warmup"):
            for key, (c, info) in by_key.items():
                gps = sorted({
                    self._worker.plan_for(key, min(n, cfg.max_batch)).gp
                    for n in batch_sizes
                })
                for gp in gps:
                    plan = self._worker.plan_for(key, gp)
                    packed = self._worker.executor.pack(
                        plan, range(gp), [c] * gp, [info] * gp)
                    self._worker.executor.collect(
                        self._worker.executor.run(packed))
                    n_traced += 1
        self.stats.count("warmup_programs", n_traced)
        return n_traced

    def queue_depth(self) -> int:
        return self._admit_q.qsize() + self._batcher.depth()

    def snapshot(self) -> dict:
        return self.stats.snapshot(queue_depth=self.queue_depth())


def submit_many(
    clusters: Sequence[Sequence[ReadScores]],
    config: Optional[ServeConfig] = None,
    server: Optional[ConsensusServer] = None,
    deadline_ms: Optional[float] = None,
) -> List[Response]:
    """Synchronously serve a list of clusters; returns Responses aligned
    with the input order.

    Rides the backpressure protocol for the caller: on QueueFullError it
    waits for the oldest in-flight request to finish and retries. Other
    admission rejections (oversize, empty) become ``ok=False``
    Responses so alignment with the input list is preserved.
    """
    own = server is None
    srv = server if server is not None else ConsensusServer(config)
    try:
        slots: List[object] = [None] * len(clusters)
        inflight: deque = deque()
        for i, c in enumerate(clusters):
            while True:
                try:
                    fut = srv.submit(c, request_id=f"c{i}",
                                     deadline_ms=deadline_ms)
                    slots[i] = fut
                    inflight.append(fut)
                    break
                except QueueFullError:
                    if inflight:
                        inflight.popleft().result()
                    else:
                        time.sleep(1e-3)
                except ServeError as e:
                    slots[i] = e
                    break
        out: List[Response] = []
        for i, s in enumerate(slots):
            if isinstance(s, ServeError):
                out.append(Response(id=f"c{i}", ok=False, error=s,
                                    path="rejected"))
            else:
                out.append(s.result())
        return out
    finally:
        if own:
            srv.close()
