"""ConsensusServer: the online consensus service front-end.

Three worker threads plus a supervisor cooperate:

- the CALLER thread runs ``submit()``: admission checks (empty /
  oversize / closed / unhealthy / queue-full) happen synchronously so
  typed errors reach the caller immediately — backpressure is an
  exception, never a block;
- the BATCHER thread drains the admission queue into the MicroBatcher
  and pushes due flushes (bucket-full / max-wait / deadline-risk) to
  the worker's flush queue;
- the WORKER thread (``worker.Worker.run_loop``) pipelines flushes
  through the shared ChunkExecutor with double-buffered dispatch;
- the SUPERVISOR thread heartbeats the other two. A dead worker thread
  (a crash that escaped ``except Exception`` — the SIGKILL analogue) is
  restarted after exponential backoff: the program factories are
  module-level lru-cached, so a fresh ``Worker`` re-attaches to every
  compiled executable for free. Its in-flight requests re-run one rung
  down the degradation ladder when they still hold retry budget;
  budget-exhausted ones fail with ``WorkerCrashError``. Past
  ``max_restarts`` the server declares itself UNHEALTHY: everything
  outstanding fails typed, and new submits raise
  ``ServerUnhealthyError``. A live-but-silent worker past
  ``stall_timeout_s`` is counted as a stall (observable in
  ``health()``; a thread cannot be killed, only watched).

The no-hung-futures invariant: every admitted request's future resolves
— by the worker (ok / typed error), by the ladder, by the supervisor
(crash recovery / unhealthy), or by ``close()``, whose drain deadline
expiring resolves every abandoned future with ``ServerClosedError``.

``submit()`` returns a ``concurrent.futures.Future[Response]``;
``submit_many()`` is the synchronous batch convenience that rides the
backpressure signal instead of surfacing it, with every wait bounded
(``result_timeout_s``) so a dead pipeline yields typed
``WaitTimeoutError`` responses, never a hang.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from concurrent.futures import TimeoutError as FutureTimeoutError
from queue import Empty, Full, Queue
from typing import Dict, List, Optional, Sequence

from ..models.sequences import ReadScores
from .batcher import MicroBatcher
from .errors import (
    EmptyClusterError,
    OversizeError,
    QueueFullError,
    ServeError,
    ServerClosedError,
    ServerUnhealthyError,
    SheddedError,
    WaitTimeoutError,
    WorkerCrashError,
)
from .faults import resolve_faults
from .quarantine import DeviceScoreboard
from .request import Request, Response, ServeConfig
from .stats import ServerStats
from .worker import STOP, Flush, Worker, respond_error

_SHUTDOWN = object()  # admission-queue shutdown sentinel
_UNSET = object()  # close(timeout=...) default marker


class ConsensusServer:
    """Online consensus with continuous micro-batching, deadlines, and
    supervised fault recovery."""

    def __init__(self, config: Optional[ServeConfig] = None,
                 stats: Optional[ServerStats] = None, start: bool = True):
        self.config = config or ServeConfig()
        self.stats = stats or ServerStats()
        self.faults = resolve_faults(self.config.faults)
        self._admit_q: Queue = Queue(maxsize=self.config.max_queue)
        self._flush_q: Queue = Queue()
        self._batcher = MicroBatcher(self.config)
        # result-integrity layer: active when the guard sentinels or
        # shadow verification are on; the scoreboard (shared by the
        # fleet) drives quarantine/probing
        self._integrity = bool(self.config.guard
                               or self.config.verify_fraction > 0)
        self.scoreboard = DeviceScoreboard(
            self.config.quarantine_threshold)
        # worker slots parked after a restart whose golden probe failed:
        # re-probed by the supervisor instead of looping restarts
        self._parked: set = set()
        if self.config.n_workers > 1 and self.config.mesh is not None:
            raise ValueError(
                "n_workers > 1 is the per-device fleet; configure mesh "
                "OR n_workers, not both"
            )
        # elastic fleet: max_workers > 0 turns autoscaling on; the
        # initial size is n_workers clamped into the elastic bounds
        cfg = self.config
        self._elastic = cfg.max_workers > 0
        if self._elastic:
            if cfg.mesh is not None:
                raise ValueError(
                    "elastic workers (max_workers > 0) are the "
                    "per-device fleet; configure mesh OR elastic "
                    "workers, not both"
                )
            if cfg.max_workers < max(1, cfg.min_workers):
                raise ValueError(
                    f"max_workers ({cfg.max_workers}) < min_workers "
                    f"({cfg.min_workers})"
                )
        n0 = max(1, cfg.n_workers)
        if self._elastic:
            n0 = min(max(n0, max(1, cfg.min_workers)), cfg.max_workers)
        # AOT executable persistence: activating installs the
        # process-wide persisted-program cache the factories consult
        # (serve.aot) — a restarted process loads the warmed grid's
        # serialized executables instead of re-tracing
        from .aot import activate as _aot_activate
        from .aot import resolve_aot_dir

        aot_dir = resolve_aot_dir(cfg.aot_cache)
        self.aot = _aot_activate(aot_dir) if aot_dir else None
        self._workers: List[Worker] = [
            self._make_worker(i) for i in range(n0)
        ]
        self._ids = itertools.count()
        self._closed = False
        self._unhealthy = False
        # every admitted, not-yet-resolved request, so close() and the
        # unhealthy transition can resolve them all (keyed by object
        # identity; a done-callback removes entries the moment any
        # resolver wins)
        self._outstanding: Dict[int, Request] = {}
        self._outstanding_lock = threading.Lock()
        self._batcher_thread: Optional[threading.Thread] = None
        self._worker_threads: List[Optional[threading.Thread]] = [
            None
        ] * len(self._workers)
        self._supervisor_thread: Optional[threading.Thread] = None
        self._stop_supervisor = threading.Event()
        self._worker_restarts = 0
        self._batcher_restarts = 0
        self._last_stall_beat: Dict[int, float] = {}
        # backoff reset (restart_backoff_reset_s): a crash after a
        # sustained healthy period forgives the restart history
        self._last_crash = time.perf_counter()
        # elastic slot lifecycle: draining = scale-down in progress
        # (worker finishing its burst); retired = drained and gone (the
        # slot is reusable by a later scale-up). Disjoint from _parked.
        self._draining: set = set()
        self._retired: set = set()
        self._last_scale = time.perf_counter()
        self._last_active = time.perf_counter()
        if start:
            self.start()

    # ---- lifecycle ----

    def _make_worker(self, i: int) -> Worker:
        """One worker of the (possibly single-member) fleet: beyond one
        worker, each executor pins its arrays to one device — round-robin
        over ``jax.devices()`` — and bursts are capped so the shared
        flush queue feeds the whole fleet instead of whichever worker
        woke first. The program factories are module-level lru caches
        and the persistent compilation cache is fingerprint-shared, so N
        workers still warm each bucket signature once."""
        cfg = self.config
        device = None
        burst_limit = None
        if cfg.n_workers > 1 or cfg.max_workers > 1:
            import jax

            devs = jax.devices()
            device = devs[i % len(devs)]
            # keep enough drained flushes to double-buffer (pack k+1
            # overlaps run k) without starving the other workers
            burst_limit = 2
        return Worker(cfg, self.stats, self.faults, device=device,
                      burst_limit=burst_limit,
                      scoreboard=(self.scoreboard if self._integrity
                                  else None))

    @property
    def _worker(self) -> Worker:
        # single-worker accessor (warmup, tests); worker 0 is the
        # fleet's representative — every worker shares its stats object
        # and program factories
        return self._workers[0]

    def start(self) -> "ConsensusServer":
        if self._batcher_thread is not None:
            return self
        self._batcher_thread = self._spawn_batcher()
        for i in range(len(self._workers)):
            self._worker_threads[i] = self._spawn_worker(i)
        if self.config.supervise:
            st = threading.Thread(target=self._supervise_loop,
                                  daemon=True,
                                  name="rifraf-serve-supervisor")
            self._supervisor_thread = st
            st.start()
        return self

    def _spawn_batcher(self) -> threading.Thread:
        bt = threading.Thread(target=self._batch_loop, daemon=True,
                              name="rifraf-serve-batcher")
        bt.start()
        return bt

    def _spawn_worker(self, i: int = 0) -> threading.Thread:
        wt = threading.Thread(target=self._workers[i].run_loop,
                              args=(self._flush_q,), daemon=True,
                              name=f"rifraf-serve-worker-{i}")
        wt.start()
        return wt

    def close(self, timeout=_UNSET) -> None:
        """Drain pending work with a deadline, then stop every thread
        and resolve whatever is left.

        ``timeout`` defaults to ``config.close_timeout_s`` (None = wait
        forever). When the deadline expires with requests still
        unresolved, each abandoned future is resolved with
        ``ServerClosedError`` — a closed server NEVER leaves a caller
        blocked on ``.result()``. submit() afterwards raises
        ServerClosedError."""
        if self._closed:
            return
        self._closed = True
        if timeout is _UNSET:
            timeout = self.config.close_timeout_s
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)

        def remaining() -> Optional[float]:
            if deadline is None:
                return None
            return max(0.0, deadline - time.perf_counter())

        # supervisor first: a restart racing the shutdown would re-spawn
        # the threads being joined
        self._stop_supervisor.set()
        if self._supervisor_thread is not None:
            self._supervisor_thread.join(remaining())
        if self._batcher_thread is not None:
            self._admit_q.put(_SHUTDOWN)
            self._batcher_thread.join(remaining())
            # one STOP per LIVE worker: each sentinel terminates exactly
            # one consumer of the shared flush queue. Retired/parked
            # slots have no consumer, and a worker draining for
            # scale-down exits on its own — if it grabs a STOP first
            # that still just ends it, and a leftover sentinel in an
            # empty queue is inert
            for wt in self._worker_threads:
                if wt is not None and wt.is_alive():
                    self._flush_q.put(STOP)
            for wt in self._worker_threads:
                if wt is not None:
                    wt.join(remaining())
        # the no-hung-futures invariant: anything still unresolved —
        # deadline expired mid-drain, worker dead, never started —
        # resolves typed right now
        for req in self._take_outstanding():
            respond_error(req, ServerClosedError(
                f"request {req.id}: abandoned by close()"
            ), self.stats, "closed_abandoned")

    def __enter__(self) -> "ConsensusServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ---- the outstanding-request registry ----

    def _track(self, req: Request) -> None:
        key = id(req)
        with self._outstanding_lock:
            self._outstanding[key] = req
        req.future.add_done_callback(
            lambda _f, k=key: self._untrack(k))

    def _untrack(self, key: int) -> None:
        with self._outstanding_lock:
            self._outstanding.pop(key, None)

    def _take_outstanding(self) -> List[Request]:
        with self._outstanding_lock:
            reqs = list(self._outstanding.values())
            self._outstanding.clear()
        return reqs

    # ---- admission (caller thread) ----

    def submit(self, cluster: Sequence[ReadScores], *,
               request_id: Optional[str] = None,
               deadline_ms: Optional[float] = None):
        """Admit one cluster; returns Future[Response].

        Raises synchronously: ServerClosedError, ServerUnhealthyError
        (worker crash loop — the supervisor gave up), EmptyClusterError,
        InvalidRequestError (typed validation — e.g. zero-length reads),
        OversizeError (hard shape limits), QueueFullError (bounded
        admission queue — the backpressure signal; back off and retry).
        """
        from ..engine.validate import InvalidInputError, \
            validate_encoded_cluster
        from ..parallel.sweep_sharded import bucket_key, cluster_info
        from .errors import InvalidRequestError

        if self._closed:
            raise ServerClosedError("server is closed")
        if self._unhealthy:
            raise ServerUnhealthyError(
                "server is unhealthy (worker restart cap exceeded)"
            )
        if not cluster:
            raise EmptyClusterError("request carries no reads")
        try:
            validate_encoded_cluster(cluster, source="submit")
        except InvalidInputError as e:
            # wrapped as a ServeError so serve_stream's typed-rejection
            # handling catches it like every other admission refusal
            self.stats.count("rejected_invalid")
            raise InvalidRequestError(f"[{e.code}] {e}") from e
        cfg = self.config
        info = cluster_info(cluster, cfg.band_growth)
        if info.n_reads > cfg.max_reads or info.max_len > cfg.max_len:
            raise OversizeError(
                f"cluster shape ({info.n_reads} reads, max len "
                f"{info.max_len}) exceeds hard limits "
                f"({cfg.max_reads} reads, len {cfg.max_len})"
            )
        # deadline-aware load shedding: refuse a request whose deadline
        # the queue ahead of it would already consume, with a
        # retry-after hint, instead of queueing it to time out.
        # Deadline-free requests are never shed (nothing to miss), and
        # an un-seeded estimator admits everything — shedding needs
        # evidence, not priors
        if cfg.shed and deadline_ms is not None:
            est = self._estimated_wait_s()
            budget = deadline_ms / 1e3
            if est is not None and est > budget:
                self.stats.count("shedded")
                raise SheddedError(
                    f"estimated queue service time {est:.3f}s exceeds "
                    f"the {budget:.3f}s deadline budget",
                    retry_after_s=max(0.0, est - budget),
                )
        # the admit fault site: after validation, before the queue — an
        # injected error here reaches the CALLER, like any admission
        # rejection
        self.faults.fire("admit")
        now = time.perf_counter()
        req = Request(
            id=request_id if request_id is not None
            else f"r{next(self._ids)}",
            cluster=list(cluster),
            info=info,
            key=bucket_key(info, cfg.read_bucket, cfg.band_bucket,
                           cfg.len_bucket),
            t_submit=now,
            deadline=(now + deadline_ms / 1e3
                      if deadline_ms is not None else None),
        )
        oversize_for_batch = (
            info.n_reads > cfg.batch_max_reads
            or info.max_len > cfg.batch_max_len
            or info.entry_k > cfg.batch_max_band
        )
        kind = "fallback" if oversize_for_batch else "batch"
        try:
            self._admit_q.put_nowait((kind, req))
        except Full:
            self.stats.count("rejected_queue_full")
            raise QueueFullError(
                f"admission queue at capacity ({cfg.max_queue})"
            ) from None
        self._track(req)
        self.stats.count("submitted")
        return req.future

    # ---- batcher thread ----

    def _batch_loop(self) -> None:
        from .errors import DeadlineExceededError

        while True:
            timeout = self._batcher.next_due(time.perf_counter())
            try:
                item = self._admit_q.get(timeout=timeout)
            except Empty:
                item = None
            if item is _SHUTDOWN:
                # drain: everything already admitted still runs
                while True:
                    try:
                        kind, req = self._admit_q.get_nowait()
                    except Empty:
                        break
                    self._route(kind, req)
                for bucket in self._batcher.drain():
                    self._flush("batch", bucket, "flush_drain")
                return
            if item is not None:
                kind, req = item
                if req.expired():
                    respond_error(req, DeadlineExceededError(
                        f"request {req.id}: deadline passed in queue"
                    ), self.stats, "rejected_deadline")
                else:
                    self._route(kind, req)
            for bucket in self._batcher.due(time.perf_counter()):
                self._flush("batch", bucket, "flush_timer")

    def _route(self, kind: str, req: Request) -> None:
        if kind == "fallback":
            self._flush("fallback", [req], "flush_fallback")
            return
        full = self._batcher.add(req)
        if full is not None:
            self._flush("batch", full, "flush_full")

    def _flush(self, kind: str, requests: List[Request],
               counter: str) -> None:
        self.stats.count(counter)
        self._flush_q.put(Flush(kind, requests))

    # ---- supervisor thread ----

    def _supervise_loop(self) -> None:
        interval = self.config.supervise_interval_s
        while not self._stop_supervisor.wait(interval):
            if self._closed:
                return
            try:
                self._check_batcher()
                self._check_worker()
                self._elastic_tick()
            except Exception:  # noqa: BLE001 — the watchdog must live
                self.stats.count("supervisor_errors")

    def _note_crash(self) -> None:
        """Crash bookkeeping with backoff reset: a crash arriving after
        ``restart_backoff_reset_s`` of clean running forgives the
        restart history — the exponential backoff and the unhealthy cap
        measure crash LOOPS, not isolated transients spread over
        hours."""
        now = time.perf_counter()
        if (now - self._last_crash
                >= self.config.restart_backoff_reset_s
                and (self._worker_restarts or self._batcher_restarts)):
            self._worker_restarts = 0
            self._batcher_restarts = 0
            self.stats.count("backoff_resets")
        self._last_crash = now

    def _check_batcher(self) -> None:
        bt = self._batcher_thread
        if bt is None or bt.is_alive():
            return
        self.stats.count("batcher_crashes")
        self._note_crash()
        if self._batcher_restarts >= self.config.max_restarts:
            self._declare_unhealthy()
            return
        self._backoff(self._batcher_restarts)
        if self._closed or self._stop_supervisor.is_set():
            return
        self._batcher_restarts += 1
        self.stats.count("batcher_restarts")
        # MicroBatcher state lives on self and survives the thread; a
        # restarted loop picks the pending buckets straight back up
        self._batcher_thread = self._spawn_batcher()

    def _check_worker(self) -> None:
        for i in range(len(self._workers)):
            self._check_worker_slot(i)

    def _check_worker_slot(self, i: int) -> None:
        wt = self._worker_threads[i]
        w = self._workers[i]
        if i in self._retired:
            return
        if i in self._draining:
            if wt is not None and wt.is_alive():
                return  # still finishing its in-flight burst
            # thread gone: either a clean drain (w.drained) or a crash
            # mid-final-burst. Either way the slot retires — it was
            # being removed — but a crash's in-flight flushes re-enter
            # the queue for the rest of the fleet like any crash
            # recovery (no restart, no budget)
            self._draining.discard(i)
            self._retired.add(i)
            self._worker_threads[i] = None
            self.stats.count("scale_down_retired")
            if not w.drained:
                self.stats.count("worker_crashes")
                self._requeue_crashed(w.take_inflight())
            return
        if i in self._parked:
            # a restarted worker whose golden probe failed: no thread
            # is running, and that is NOT a crash — re-probe (rate
            # limited) and spawn only on a clean pass. The restart
            # budget is untouched: a chip that cannot answer the
            # known-answer problem is quarantined, not restart-looped.
            if (time.perf_counter() - w._last_probe
                    >= self.config.probe_interval_s
                    and w.golden_probe()):
                self._parked.discard(i)
                self._worker_threads[i] = self._spawn_worker(i)
            return
        if wt is not None and wt.is_alive():
            # alive: watch for a stall (busy with no heartbeat). One
            # count per stalled burst — last_beat only moves when the
            # worker does, so it keys the episode.
            if w.busy:
                age = time.perf_counter() - w.last_beat
                if (age > self.config.stall_timeout_s
                        and w.last_beat != self._last_stall_beat.get(i)):
                    self._last_stall_beat[i] = w.last_beat
                    self.stats.count("worker_stalls")
            return
        # dead worker: the crash escaped every except-Exception layer.
        # The restart budget is FLEET-WIDE — a crash loop on any device
        # exhausts it, exactly like the single-worker server.
        self.stats.count("worker_crashes")
        self._note_crash()
        crashed = w.take_inflight()
        if self._worker_restarts >= self.config.max_restarts:
            self._declare_unhealthy(crashed)
            return
        self._backoff(self._worker_restarts)
        if self._closed or self._stop_supervisor.is_set():
            return  # close() resolves the crashed requests
        self._worker_restarts += 1
        self.stats.count("worker_restarts")
        # a fresh Worker re-attaches to the module-level lru-cached
        # program factories: no recompilation, same executables.
        # Crashed flushes re-queue FIRST so fleet mates can take them
        # while this slot proves itself.
        self._workers[i] = self._make_worker(i)
        self._requeue_crashed(crashed)
        if self._integrity and not self._workers[i].golden_probe():
            # failed the post-restart known-answer probe: park the slot
            # (quarantined on the scoreboard) instead of rejoining the
            # round-robin with a chip that returns wrong answers
            self._worker_threads[i] = None
            self._parked.add(i)
            return
        self._worker_threads[i] = self._spawn_worker(i)

    def _backoff(self, k: int) -> None:
        # interruptible exponential backoff before restart k
        self._stop_supervisor.wait(
            self.config.restart_backoff_s * (2 ** k))

    def _requeue_crashed(self, flushes: List[Flush]) -> None:
        """Crash recovery for the dead worker's in-flight requests:
        re-run each one rung DOWN the ladder while it has retry budget
        (a crashed rung-0 batch re-runs whole-block; anything deeper
        re-runs per-request fallback; a crashed fallback retries as
        fallback — transient faults clear, persistent ones exhaust the
        budget). Budget-exhausted requests fail with WorkerCrashError."""
        for flush in flushes:
            retryable: List[Request] = []
            for r in flush.requests:
                if r.future.done():
                    continue
                if r.retries < self.config.max_retries:
                    r.retries += 1
                    retryable.append(r)
                else:
                    self.stats.count("ladder_exhausted")
                    respond_error(r, WorkerCrashError(
                        f"request {r.id}: worker crashed and the retry "
                        f"budget is spent"
                    ), self.stats, "failed_crash")
            if not retryable:
                continue
            if flush.kind == "batch" and flush.rung == 0:
                self.stats.count("ladder_retry_block", len(retryable))
                self._flush_q.put(Flush("batch", retryable, 1))
            else:
                self.stats.count("ladder_retry_fallback",
                                 len(retryable))
                for r in retryable:
                    self._flush_q.put(Flush("fallback", [r], 2))

    # ---- elastic fleet (supervisor thread) ----

    def _active_slots(self) -> List[int]:
        """Worker slots currently serving traffic: thread running, not
        parked (failed probe), not draining (scale-down in progress),
        not retired. This is the population the elastic targets count —
        a parked slot is capacity the fleet does NOT have."""
        return [
            i for i in range(len(self._workers))
            if i not in self._parked
            and i not in self._draining
            and i not in self._retired
            and self._worker_threads[i] is not None
            and self._worker_threads[i].is_alive()
        ]

    def _estimated_wait_s(self) -> Optional[float]:
        """Expected queue service time for a request admitted NOW:
        outstanding work times the per-request service EWMA, divided
        across the active fleet. None until the first completion has
        seeded the estimator (an un-seeded server never sheds)."""
        service = self.stats.service_estimate()
        if service is None:
            return None
        with self._outstanding_lock:
            n_out = len(self._outstanding)
        return n_out * service / max(1, len(self._active_slots()))

    def _elastic_tick(self) -> None:
        """One autoscaling decision: grow on queue pressure (depth or
        time-in-queue), drain the highest slot after sustained idleness,
        never outside [min_workers or 1, max_workers], at most one
        resize per cooldown window."""
        if not self._elastic or self._closed or self._unhealthy:
            return
        cfg = self.config
        now = time.perf_counter()
        active = self._active_slots()
        n = len(active)
        depth = (self._admit_q.qsize() + self._batcher.depth()
                 + self._flush_q.qsize())
        if depth > 0 or any(self._workers[i].busy for i in active):
            self._last_active = now
        if now - self._last_scale < cfg.scale_cooldown_s:
            return
        lo = max(1, cfg.min_workers)
        wait = self.stats.queue_wait_estimate()
        pressed = depth > 0 and (
            depth > cfg.scale_up_depth * max(1, n)
            or (wait is not None and wait > cfg.scale_up_wait_s)
        )
        # the ceiling counts PROVISIONED slots (parked and draining
        # included), not just active ones: a fleet whose recruits keep
        # failing the golden probe must park at max_workers slots and
        # stop, not mint parked workers forever
        n_prov = len(self._workers) - len(self._retired)
        if n_prov < cfg.max_workers and (pressed or n < lo):
            self._scale_up()
            self._last_scale = now
        elif (n > lo and depth == 0
              and now - self._last_active >= cfg.scale_down_idle_s):
            self._scale_down(max(active))
            self._last_scale = now

    def _scale_up(self) -> None:
        """Add one worker: reuse the lowest retired slot if any, else
        append a new one. The recruit passes the golden probe before
        joining the round-robin when the integrity layer is on — a bad
        chip parks instead of serving wrong answers (same contract as a
        post-crash restart)."""
        if self._retired:
            i = min(self._retired)
            self._retired.discard(i)
        else:
            i = len(self._workers)
            self._workers.append(None)  # placed just below
            self._worker_threads.append(None)
        w = self._make_worker(i)
        self._workers[i] = w
        self.stats.count("scale_up_events")
        if self._integrity and not w.golden_probe():
            self._worker_threads[i] = None
            self._parked.add(i)
            return
        self._worker_threads[i] = self._spawn_worker(i)

    def _scale_down(self, i: int) -> None:
        """Begin a graceful drain of slot ``i``: the worker finishes
        whatever burst it already holds, requeues nothing, resolves
        every future it owns, then exits its loop on its own — the
        supervisor retires the slot once the thread is gone
        (``_check_worker_slot``)."""
        self._workers[i].draining = True
        self._draining.add(i)
        self.stats.count("scale_down_events")

    def _declare_unhealthy(self,
                           crashed: Sequence[Flush] = ()) -> None:
        """Restart cap exceeded (crash loop): stop taking traffic and
        fail everything outstanding with a typed error — an unhealthy
        server still never hangs a future."""
        if self._unhealthy:
            return
        self._unhealthy = True
        self.stats.count("declared_unhealthy")
        err = WorkerCrashError(
            "server unhealthy: worker restart cap "
            f"({self.config.max_restarts}) exceeded"
        )
        for flush in crashed:
            for r in flush.requests:
                respond_error(r, err, self.stats, "failed_crash")
        for req in self._take_outstanding():
            respond_error(req, err, self.stats, "failed_crash")

    # ---- warmup / observability ----

    def warmup(self, example_clusters: Sequence[Sequence[ReadScores]],
               batch_sizes: Sequence[int] = (1,)) -> int:
        """Pre-trace the bucket-grid executables before taking traffic.

        Groups the examples by routing signature and runs one synthetic
        micro-batch per (signature, padded batch size) through the
        ChunkExecutor — with the fingerprinted XLA compilation cache
        enabled, so a restarted server rehydrates from disk instead of
        recompiling. Returns the number of executables exercised.
        """
        from ..engine.driver import _enable_compilation_cache
        from ..parallel.sweep_sharded import bucket_key, cluster_info

        _enable_compilation_cache()
        cfg = self.config
        by_key = {}
        for c in example_clusters:
            info = cluster_info(c, cfg.band_growth)
            key = bucket_key(info, cfg.read_bucket, cfg.band_bucket,
                             cfg.len_bucket)
            by_key.setdefault(key, (list(c), info))
        n_traced = 0
        with self.stats.timers.time("serve_warmup"):
            for key, (c, info) in by_key.items():
                gps = sorted({
                    self._worker.plan_for(key, min(n, cfg.max_batch)).gp
                    for n in batch_sizes
                })
                for gp in gps:
                    plan = self._worker.plan_for(key, gp)
                    packed = self._worker.executor.pack(
                        plan, range(gp), [c] * gp, [info] * gp)
                    self._worker.executor.collect(
                        self._worker.executor.run(packed))
                    n_traced += 1
        self.stats.count("warmup_programs", n_traced)
        if self._integrity:
            # every fleet member proves itself on the known-answer
            # golden problem before taking traffic; a failing device
            # starts quarantined (its run_loop refuses flushes and
            # re-probes until clean)
            for w in self._workers:
                if not w.golden_probe():
                    self.stats.count("warmup_probe_failures")
        return n_traced

    def queue_depth(self) -> int:
        return self._admit_q.qsize() + self._batcher.depth()

    def health(self) -> dict:
        """Liveness/supervision snapshot (JSON-serializable): thread
        liveness, worker heartbeat age, restart and stall counts, the
        retry-ladder counters, outstanding-request count, and the
        fault plan's fire accounting when faults are configured."""
        bt = self._batcher_thread
        now = time.perf_counter()
        # retired slots are capacity the fleet gave BACK (elastic
        # scale-down); they are not dead workers, so every fleet rollup
        # here excludes them
        live_idx = [i for i in range(len(self._workers))
                    if i not in self._retired]
        alive = {
            i: bool(self._worker_threads[i] is not None
                    and self._worker_threads[i].is_alive())
            for i in live_idx
        }
        out = {
            "healthy": not (self._unhealthy or self._closed),
            "closed": self._closed,
            "unhealthy": self._unhealthy,
            "batcher_alive": bool(bt is not None and bt.is_alive()),
            # fleet semantics: alive means EVERY (non-retired) worker
            # thread is running; busy means any of them is; the flush
            # age is the freshest heartbeat (per-worker detail in
            # "workers")
            "worker_alive": all(alive.values()) if alive else False,
            "worker_busy": any(self._workers[i].busy
                               for i in live_idx),
            "last_flush_age_s": round(
                now - max(self._workers[i].last_beat
                          for i in live_idx), 3
            ) if live_idx else None,
            "n_workers": len(live_idx),
            "worker_restarts": self._worker_restarts,
            "batcher_restarts": self._batcher_restarts,
            "retry_ladder": self.stats.ladder(),
            "outstanding": len(self._outstanding),
        }
        if len(self._workers) > 1:
            out["workers"] = [
                {
                    "slot": i,
                    "alive": alive[i],
                    "busy": self._workers[i].busy,
                    "last_flush_age_s": round(
                        now - self._workers[i].last_beat, 3),
                    "device": str(self._workers[i].device)
                    if self._workers[i].device is not None else None,
                }
                for i in live_idx
            ]
        if self._elastic:
            out["elastic"] = {
                "min_workers": max(1, self.config.min_workers),
                "max_workers": self.config.max_workers,
                "active_workers": len(self._active_slots()),
                "draining": sorted(self._draining),
                "retired": sorted(self._retired),
                "scale_up_events": self.stats.get("scale_up_events"),
                "scale_down_events":
                    self.stats.get("scale_down_events"),
                "backoff_resets": self.stats.get("backoff_resets"),
            }
        if self.config.shed:
            est = self._estimated_wait_s()
            out["shed"] = {
                "enabled": True,
                "shedded": self.stats.get("shedded"),
                "estimated_wait_s": round(est, 4)
                if est is not None else None,
            }
        if self.aot is not None:
            out["aot"] = self.aot.snapshot()
        if self._integrity:
            out["integrity"] = {
                "guard": self.config.guard,
                "verify_fraction": self.config.verify_fraction,
                "quarantine_threshold":
                    self.config.quarantine_threshold,
                "devices": self.scoreboard.snapshot(),
                "counters": self.stats.integrity(),
                "parked_workers": sorted(self._parked),
            }
        if self.faults:
            out["faults"] = self.faults.snapshot()
        return out

    def snapshot(self) -> dict:
        out = self.stats.snapshot(queue_depth=self.queue_depth())
        out["health"] = self.health()
        return out


def submit_many(
    clusters: Sequence[Sequence[ReadScores]],
    config: Optional[ServeConfig] = None,
    server: Optional[ConsensusServer] = None,
    deadline_ms: Optional[float] = None,
) -> List[Response]:
    """Synchronously serve a list of clusters; returns Responses aligned
    with the input order.

    Rides the backpressure protocol for the caller: on QueueFullError it
    waits for the oldest in-flight request to finish and retries. Other
    admission rejections (oversize, empty, unhealthy) become ``ok=False``
    Responses so alignment with the input list is preserved.

    Every wait is bounded by ``config.result_timeout_s`` (tightened by
    ``deadline_ms`` when given): a dead or wedged pipeline yields typed
    ``WaitTimeoutError`` / ``QueueFullError`` responses instead of
    blocking this call forever.
    """
    own = server is None
    srv = server if server is not None else ConsensusServer(config)
    cfg = srv.config
    # how long any single wait may block: the request deadline plus the
    # flush margin when a deadline exists, the global cap otherwise
    wait_s = cfg.result_timeout_s
    if deadline_ms is not None:
        wait_s = min(wait_s,
                     deadline_ms / 1e3 + cfg.result_timeout_s / 10.0)
    try:
        slots: List[object] = [None] * len(clusters)
        inflight: deque = deque()
        for i, c in enumerate(clusters):
            t0 = time.perf_counter()
            while True:
                try:
                    fut = srv.submit(c, request_id=f"c{i}",
                                     deadline_ms=deadline_ms)
                    slots[i] = fut
                    inflight.append(fut)
                    break
                except QueueFullError as e:
                    # bounded backpressure: wait for the oldest
                    # in-flight slot, but give up on this submission
                    # once the budget is spent (a dead worker never
                    # frees the queue)
                    if time.perf_counter() - t0 > wait_s:
                        slots[i] = e
                        break
                    if inflight:
                        try:
                            inflight.popleft().result(timeout=min(
                                1.0, wait_s))
                        except FutureTimeoutError:
                            pass
                    else:
                        time.sleep(1e-3)
                except ServeError as e:
                    slots[i] = e
                    break
        out: List[Response] = []
        for i, s in enumerate(slots):
            if isinstance(s, ServeError):
                out.append(Response(id=f"c{i}", ok=False, error=s,
                                    path="rejected"))
                continue
            try:
                out.append(s.result(timeout=wait_s))
            except FutureTimeoutError:
                srv.stats.count("wait_timeouts")
                out.append(Response(
                    id=f"c{i}", ok=False,
                    error=WaitTimeoutError(
                        f"request c{i}: no result within {wait_s:g}s"
                    ),
                    path="rejected",
                ))
        return out
    finally:
        if own:
            srv.close()
