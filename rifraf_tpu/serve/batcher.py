"""Continuous micro-batcher: per-shape-bucket pending queues + flush policy.

Pure single-threaded logic (the server's batcher thread drives it with a
monotonic clock), so the flush policy is testable with a fake clock and
no JAX. Requests are grouped by their sweep-scheduler shape key
(``parallel.sweep_sharded.bucket_key``); with segment packing enabled
(the default), small same-shape requests group by the SHAPE axes only
(Lpad, Tmax, K0) — the worker packs them into shared lane blocks at
read granularity, so Npad no longer separates them. A bucket flushes
when

- it reaches ``max_batch`` requests (occupancy flush),
- its pending requests fill the 128-lane vector axis (lane-capacity
  flush — the launch's read lanes are full, so waiting longer only adds
  lane tiles). The demand is the POST-PACKING lane count: pending reads
  for a segment-packed bucket, ``pending * Npad`` for a whole-block
  bucket. Counting ``pending * Npad`` for packed buckets would
  over-flush — a 5-read request reserves 5 lanes in a shared block, not
  its whole Npad=8 block,
- its OLDEST request has waited ``max_wait_ms`` (latency flush), or
- any member's deadline is within ``deadline_margin_ms`` (deadline-risk
  flush — dispatch now or miss it).

gpuPairHMM and Endeavor (PAPERS.md) both find that this batching/padding
policy, not kernel speed, dominates online throughput: max_wait trades
tail latency for occupancy, and the shape-keyed grouping keeps padding
waste at offline-sweep levels instead of pad-to-global-maxima.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..parallel.sweep_sharded import SEG_TMAX_MAX, segment_pack_enabled
from .request import Request, ServeConfig


def resolve_segment_pack(config: ServeConfig) -> bool:
    """Whether this server packs cross-request at read granularity:
    the config field when set, else the ``RIFRAF_TPU_SEGMENT_PACK`` env
    gate; always off without a lane target (nothing to fill)."""
    sp = config.segment_pack
    if sp is None:
        sp = segment_pack_enabled()
    return bool(sp) and config.lane_target > 0


def segment_eligible(key, lane_target: int) -> bool:
    """Whether a request of bucket ``key`` can share a lane block:
    small enough to leave room (Npad below the lane target) and short
    enough for the unblocked dense sweep (the same decline conditions
    as plan_sweep)."""
    return key[0] < lane_target and key[2] + 1 <= SEG_TMAX_MAX


class MicroBatcher:
    """Pending-request store keyed by bucket signature."""

    def __init__(self, config: ServeConfig):
        self.config = config
        self.segment_pack = resolve_segment_pack(config)
        # the batcher thread owns the flush policy, but depth() is read
        # by the caller path (queue_depth) and the supervisor's elastic
        # tick — iterating _pending while the batcher mutates it raises
        # RuntimeError, so every access goes through _lock
        self._lock = threading.Lock()
        self._pending: Dict[Tuple, List[Request]] = {}

    def depth(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._pending.values())

    def _group_key(self, req: Request) -> Tuple:
        if self.segment_pack and segment_eligible(
            req.key, self.config.lane_target
        ):
            return ("seg",) + tuple(req.key[1:])
        return ("blk",) + tuple(req.key)

    def _lane_demand(self, key: Tuple, bucket: List[Request]) -> int:
        """Post-packing lane demand of one pending bucket: reads for a
        segment-packed group (requests share blocks at read
        granularity; info-less requests fall back to their Npad), whole
        Npad blocks otherwise."""
        if key[0] == "seg":
            return sum(
                r.info.n_reads if r.info is not None else r.key[0]
                for r in bucket
            )
        return sum(r.key[0] for r in bucket)

    def add(self, req: Request) -> Optional[List[Request]]:
        """Admit one request; returns a full bucket's flush (in arrival
        order) when this request filled it — by request count
        (``max_batch``) or by lane capacity (``lane_target`` read
        lanes, post-packing demand) — else None."""
        key = self._group_key(req)
        with self._lock:
            bucket = self._pending.setdefault(key, [])
            bucket.append(req)
            lane_target = self.config.lane_target
            if len(bucket) >= self.config.max_batch or (
                lane_target > 0
                and self._lane_demand(key, bucket) >= lane_target
            ):
                return self._pending.pop(key)
        return None

    def due(self, now: float) -> List[List[Request]]:
        """Buckets whose max-wait or deadline-risk timer has expired."""
        max_wait = self.config.max_wait_ms / 1e3
        margin = self.config.deadline_margin_ms / 1e3
        flushes = []
        with self._lock:
            for key in list(self._pending):
                bucket = self._pending[key]
                oldest_wait = now - bucket[0].t_submit
                deadlines = [r.deadline for r in bucket
                             if r.deadline is not None]
                at_risk = deadlines and min(deadlines) - now <= margin
                if oldest_wait >= max_wait or at_risk:
                    flushes.append(self._pending.pop(key))
        return flushes

    def next_due(self, now: float) -> Optional[float]:
        """Seconds until the earliest pending timer fires (>= 0), or
        None when nothing is pending — the batcher thread's poll
        timeout."""
        max_wait = self.config.max_wait_ms / 1e3
        margin = self.config.deadline_margin_ms / 1e3
        t_next = None
        with self._lock:
            for bucket in self._pending.values():
                t = bucket[0].t_submit + max_wait
                for r in bucket:
                    if r.deadline is not None:
                        t = min(t, r.deadline - margin)
                t_next = t if t_next is None else min(t_next, t)
        if t_next is None:
            return None
        return max(t_next - now, 0.0)

    def drain(self) -> List[List[Request]]:
        """Flush everything (shutdown)."""
        with self._lock:
            out = list(self._pending.values())
            self._pending.clear()
        return out
