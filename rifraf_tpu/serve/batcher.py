"""Continuous micro-batcher: per-shape-bucket pending queues + flush policy.

Pure single-threaded logic (the server's batcher thread drives it with a
monotonic clock), so the flush policy is testable with a fake clock and
no JAX. Requests are grouped by their sweep-scheduler shape key
(``parallel.sweep_sharded.bucket_key``); a bucket flushes when

- it reaches ``max_batch`` requests (occupancy flush),
- its pending requests fill the 128-lane vector axis,
  ``pending * Npad >= lane_target`` (lane-capacity flush — the launch's
  read lanes are full, so waiting longer only adds lane tiles),
- its OLDEST request has waited ``max_wait_ms`` (latency flush), or
- any member's deadline is within ``deadline_margin_ms`` (deadline-risk
  flush — dispatch now or miss it).

gpuPairHMM and Endeavor (PAPERS.md) both find that this batching/padding
policy, not kernel speed, dominates online throughput: max_wait trades
tail latency for occupancy, and the shape-keyed grouping keeps padding
waste at offline-sweep levels instead of pad-to-global-maxima.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .request import Request, ServeConfig


class MicroBatcher:
    """Pending-request store keyed by bucket signature."""

    def __init__(self, config: ServeConfig):
        self.config = config
        self._pending: Dict[Tuple[int, int, int, int], List[Request]] = {}

    def depth(self) -> int:
        return sum(len(v) for v in self._pending.values())

    def add(self, req: Request) -> Optional[List[Request]]:
        """Admit one request; returns a full bucket's flush (in arrival
        order) when this request filled it — by request count
        (``max_batch``) or by lane capacity (``lane_target`` read lanes,
        ``req.key[0]`` = Npad reads per cluster) — else None."""
        bucket = self._pending.setdefault(req.key, [])
        bucket.append(req)
        lane_target = self.config.lane_target
        if len(bucket) >= self.config.max_batch or (
            lane_target > 0 and len(bucket) * req.key[0] >= lane_target
        ):
            return self._pending.pop(req.key)
        return None

    def due(self, now: float) -> List[List[Request]]:
        """Buckets whose max-wait or deadline-risk timer has expired."""
        max_wait = self.config.max_wait_ms / 1e3
        margin = self.config.deadline_margin_ms / 1e3
        flushes = []
        for key in list(self._pending):
            bucket = self._pending[key]
            oldest_wait = now - bucket[0].t_submit
            deadlines = [r.deadline for r in bucket if r.deadline is not None]
            at_risk = deadlines and min(deadlines) - now <= margin
            if oldest_wait >= max_wait or at_risk:
                flushes.append(self._pending.pop(key))
        return flushes

    def next_due(self, now: float) -> Optional[float]:
        """Seconds until the earliest pending timer fires (>= 0), or
        None when nothing is pending — the batcher thread's poll
        timeout."""
        max_wait = self.config.max_wait_ms / 1e3
        margin = self.config.deadline_margin_ms / 1e3
        t_next = None
        for bucket in self._pending.values():
            t = bucket[0].t_submit + max_wait
            for r in bucket:
                if r.deadline is not None:
                    t = min(t, r.deadline - margin)
            t_next = t if t_next is None else min(t_next, t)
        if t_next is None:
            return None
        return max(t_next - now, 0.0)

    def drain(self) -> List[List[Request]]:
        """Flush everything (shutdown)."""
        out = list(self._pending.values())
        self._pending.clear()
        return out
