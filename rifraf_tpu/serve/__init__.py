"""rifraf_tpu.serve — online consensus with continuous micro-batching.

An always-on counterpart to the offline ``parallel.sweep_clusters_sharded``
sweep: requests (one read cluster each) are admitted through a bounded
queue with per-request deadlines, micro-batched by the sweep scheduler's
shape-bucket signature, and dispatched double-buffered through the SAME
lru-cached compiled programs the offline sweep uses. The server is
supervised: a fault-injection plane (``serve.faults``), a watchdog that
restarts a crashed worker thread, and a degradation ladder that retries
failed micro-batches at progressively simpler execution rungs. See
docs/serving.md.
"""

from .aot import AotCache, clear_aot_cache, resolve_aot_dir
from .batcher import MicroBatcher
from .errors import (
    DeadlineExceededError,
    EmptyClusterError,
    InvalidRequestError,
    OversizeError,
    QueueFullError,
    ServeError,
    ServerClosedError,
    ServerUnhealthyError,
    SheddedError,
    WaitTimeoutError,
    WorkerCrashError,
)
from ..engine.integrity import (
    IntegrityError,
    NumericalIntegrityError,
    ResultDivergenceError,
)
from .faults import (
    FaultPlan,
    FaultSpec,
    InjectedCrashError,
    InjectedFaultError,
)
from .quarantine import DeviceScoreboard
from .request import Request, Response, ServeConfig, encode_cluster
from .server import ConsensusServer, submit_many
from .stats import ServerStats
from .worker import InternalError

__all__ = [
    "AotCache",
    "ConsensusServer",
    "DeadlineExceededError",
    "DeviceScoreboard",
    "EmptyClusterError",
    "FaultPlan",
    "FaultSpec",
    "InjectedCrashError",
    "InjectedFaultError",
    "IntegrityError",
    "InternalError",
    "NumericalIntegrityError",
    "ResultDivergenceError",
    "InvalidRequestError",
    "MicroBatcher",
    "OversizeError",
    "QueueFullError",
    "Request",
    "Response",
    "ServeConfig",
    "ServeError",
    "ServerClosedError",
    "ServerStats",
    "ServerUnhealthyError",
    "SheddedError",
    "WaitTimeoutError",
    "WorkerCrashError",
    "clear_aot_cache",
    "encode_cluster",
    "resolve_aot_dir",
    "submit_many",
]
