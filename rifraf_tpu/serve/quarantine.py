"""Suspect-device quarantine: the per-device integrity scoreboard and
the known-answer golden probe.

A fleet you cannot trust per-device cannot be made elastic: a chip that
flips bits returns *plausible* wrong answers, so crash supervision (PR
7) never sees it. The integrity layer attributes every tripped sentinel
(``NumericalIntegrityError``) and every shadow-verification divergence
(``ResultDivergenceError``) to the device that produced the result;
this module keeps score.

Lifecycle:

1. **Scoring** — ``DeviceScoreboard.record_trip(device, kind)`` counts
   guard trips and divergences per device. Crossing
   ``quarantine_threshold`` evicts the device from the round-robin:
   its worker stops taking flushes (they re-queue for fleet mates) and
   enters the probe loop.
2. **Probing** — the golden probe (:func:`golden_problem`) is a
   deterministic known-answer cluster: error-free reads copied from a
   fixed planted template, so the only correct consensus IS the
   template. The probe runs through the worker's OWN executor on its
   OWN device; it passes iff the consensus equals the template and the
   score is finite. Also run at warmup and after every supervisor
   restart, so a freshly (re)started worker proves itself before
   rejoining the round-robin.
3. **Reinstating** — ``note_probe(device, ok=True)`` clears the
   quarantine and zeroes the trip counters; a failing probe keeps the
   device quarantined (and the supervisor keeps it parked instead of
   burning restart budget on a chip that cannot pass a 48-base
   problem).

Everything is visible in ``ConsensusServer.health()["integrity"]`` and
the ``ServerStats`` integrity counters.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

TRIP_KINDS = ("guard", "divergence")

# golden-problem constants: a fixed 48-base planted template (length
# divisible by the codon machinery), a handful of error-free copies, a
# flat high-confidence error profile. Deterministic by construction —
# no RNG state leaks into the probe.
GOLDEN_LEN = 48
GOLDEN_READS = 3
GOLDEN_LOG_P = -4.0
GOLDEN_SEED = 1729


def device_key(device) -> str:
    """Stable scoreboard key for a jax device (or None = host default)."""
    return "default" if device is None else str(device)


def golden_problem(config):
    """Build the known-answer probe: ``(cluster, template)`` where the
    cluster is ``GOLDEN_READS`` error-free copies of the planted
    template encoded with the server's own scores/bandwidth (so the
    probe exercises the same numeric path as traffic)."""
    from ..models.sequences import make_read_scores

    rng = np.random.default_rng(GOLDEN_SEED)
    template = rng.integers(0, 4, size=GOLDEN_LEN).astype(np.int8)
    log_p = np.full(GOLDEN_LEN, GOLDEN_LOG_P, dtype=np.float64)
    cluster = [
        make_read_scores(template.copy(), log_p.copy(),
                         config.bandwidth, config.scores)
        for _ in range(GOLDEN_READS)
    ]
    return cluster, template


class _DeviceScore:
    __slots__ = ("trips", "quarantined", "probes_pass", "probes_fail")

    def __init__(self):
        self.trips: Dict[str, int] = {k: 0 for k in TRIP_KINDS}
        self.quarantined = False
        self.probes_pass = 0
        self.probes_fail = 0


class DeviceScoreboard:
    """Thread-safe per-device integrity accounting.

    ``threshold`` is the total trip count (guard + divergence) at which
    a device is evicted; 0 disables eviction (trips are still counted
    and visible)."""

    def __init__(self, threshold: int = 2):
        self.threshold = int(threshold)
        self._lock = threading.Lock()
        self._scores: Dict[str, _DeviceScore] = {}

    def _get(self, key: str) -> _DeviceScore:
        sc = self._scores.get(key)
        if sc is None:
            sc = self._scores[key] = _DeviceScore()
        return sc

    def record_trip(self, device, kind: str) -> bool:
        """Count one integrity trip against ``device``. Returns True
        exactly when this trip crosses the threshold and quarantines
        the device (the caller counts the eviction)."""
        if kind not in TRIP_KINDS:
            raise ValueError(f"unknown trip kind {kind!r}")
        key = device_key(device)
        with self._lock:
            sc = self._get(key)
            sc.trips[kind] += 1
            total = sum(sc.trips.values())
            if (self.threshold > 0 and not sc.quarantined
                    and total >= self.threshold):
                sc.quarantined = True
                return True
        return False

    def quarantine(self, device) -> None:
        """Explicit eviction (warmup/restart probe failure)."""
        with self._lock:
            self._get(device_key(device)).quarantined = True

    def is_quarantined(self, device) -> bool:
        with self._lock:
            sc = self._scores.get(device_key(device))
            return bool(sc is not None and sc.quarantined)

    def note_probe(self, device, ok: bool) -> bool:
        """Record a golden-probe outcome. A passing probe REINSTATES
        the device (quarantine cleared, trip counters zeroed — it
        starts clean); a failing one quarantines it. Returns whether
        the device is quarantined after the probe."""
        with self._lock:
            sc = self._get(device_key(device))
            if ok:
                sc.probes_pass += 1
                sc.quarantined = False
                sc.trips = {k: 0 for k in TRIP_KINDS}
            else:
                sc.probes_fail += 1
                sc.quarantined = True
            return sc.quarantined

    def any_quarantined(self) -> bool:
        with self._lock:
            return any(sc.quarantined for sc in self._scores.values())

    def snapshot(self) -> dict:
        """JSON-serializable per-device state for ``health()``."""
        with self._lock:
            return {
                key: {
                    "quarantined": sc.quarantined,
                    "guard_trips": sc.trips["guard"],
                    "divergences": sc.trips["divergence"],
                    "probes_pass": sc.probes_pass,
                    "probes_fail": sc.probes_fail,
                }
                for key, sc in self._scores.items()
            }
