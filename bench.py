"""Headline benchmark: the fused consensus step on 1 kb x 256 reads.

One step = batched banded forward + backward fills plus rescoring of ALL
~9xLen single-base edits against every read — the per-iteration work of the
reference's hill-climbing loop (align.jl:155-212 fills + model.jl:242-285
rescoring, BASELINE.json config "1 kb template x 256 reads").

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

`vs_baseline` is the speedup over this repo's measured CPU-backend number
(same code, jax CPU, this host class — recorded in BASELINE.md).
"""

import json
import sys
import time

import numpy as np

# CPU backend measurement of the identical step on the dev host
# (see BASELINE.md "measured baselines"): 7.474e4 proposal-scores/sec.
CPU_BASELINE_PROPOSAL_SCORES_PER_SEC = 7.474e4

TLEN = 1000
N_READS = 256
BANDWIDTH = 16


def build_problem():
    from rifraf_tpu.engine.proposals import Deletion, Insertion, Substitution
    from rifraf_tpu.models.errormodel import ErrorModel, Scores
    from rifraf_tpu.models.sequences import batch_reads, make_read_scores

    scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
    rng = np.random.default_rng(0)
    template = rng.integers(0, 4, size=TLEN).astype(np.int8)
    reads = []
    for _ in range(N_READS):
        slen = int(rng.integers(950, 1050))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, BANDWIDTH, scores))
    batch = batch_reads(reads, dtype=np.float32)
    proposals = (
        [Substitution(p, b) for p in range(TLEN) for b in range(4)]
        + [Insertion(p, b) for p in range(TLEN + 1) for b in range(4)]
        + [Deletion(p) for p in range(TLEN)]
    )
    return template, batch, proposals


def main():
    import jax

    from rifraf_tpu.ops import align_jax
    from rifraf_tpu.ops.proposal_jax import score_proposals_batch

    template, batch, proposals = build_problem()
    P = len(proposals)

    def step():
        A, _, _, geom = align_jax.forward_batch(template, batch, want_moves=False)
        B, _, _ = align_jax.backward_batch(template, batch)
        return score_proposals_batch(A, B, batch, geom, proposals)

    # warmup / compile
    jax.block_until_ready(step())
    times = []
    for _ in range(3):
        t0 = time.time()
        jax.block_until_ready(step())
        times.append(time.time() - t0)
    dt = min(times)
    value = N_READS * P / dt
    out = {
        "metric": "proposal_scores_per_sec_1kb_256reads",
        "value": round(value, 1),
        "unit": "proposal-scores/s",
        "vs_baseline": round(value / CPU_BASELINE_PROPOSAL_SCORES_PER_SEC, 2),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
