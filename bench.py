"""Headline benchmark: END-TO-END `rifraf()` consensus, 1 kb x 256 reads.

Times the actual driver (`rifraf_tpu.engine.driver.rifraf`) — the fused
per-iteration device step (forward + backward fills + dense all-edits
rescoring in one dispatch, ops.fused), the packed device->host fetch, and
all host-side hill-climbing logic — on a seeded simulated problem: 1 kb
template, 256 phred-scored reads, no read batching (every iteration spans
the full read set, the one-consensus-per-chip configuration). This is the
reference's model.jl:679-719 realign + 385-456 rescoring loop, end to end
until convergence — NOT a microbenchmark of an unwired step.

Timing protocol: one full warm-up run compiles every bucketed shape, then
`N_TIMED` fresh runs are timed (identical seeded problem; the driver
recomputes everything — only XLA executables are reused, exactly as in
production). Reported value is the min; every individual run rides along
in the JSON (`runs_s`) so environment variance (the TPU tunnel has shown
~40% swings between rounds) is visible instead of silently folded in.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N,
   "runs_s": [...], "northstar_2048x1kb": {...}, "ref_default": {...}}

The headline metric stays the 1 kb x 256 full-batch config; the same
line also driver-captures (a) the BASELINE.json north-star config
(2048 x 1 kb, the >=50x target) and (b) the REFERENCE-DEFAULT parameter
set (fixed top-5 INIT batch, batch_size 20, alignment proposals — what
cli/consensus.py actually runs), each with its own CPU-measured
vs_baseline.

`vs_baseline` is the speedup over this repo's CPU-backend wall time for
the IDENTICAL end-to-end run on the dev host class (python bench.py --cpu
recalibrates; recorded in BASELINE.md "measured baselines").

Other modes (results appended to BASELINE.md, not the driver JSON):
  --cpu        run the selected mode on the CPU backend
  --step       the round-2 fused-step microbenchmark (proposal-scores/s)
  --northstar  2048 x 1 kb and 10 kb x 512 x band-64 end-to-end configs
  --golden     the shipped-data CLI run (vs the reference's 3.6 s anchor)
  --sweep      heterogeneous 2048-cluster sharded sweep (log-normal read
               lengths): bucketed vs uniform scheduler seconds and
               padding-waste ratios (--sweep-n / --sweep-chunk override
               the cluster count / chunk size for smoke runs)
  --serve      serve_poisson_1k: the online consensus service
               (rifraf_tpu.serve) on 1000 log-normal-length requests —
               burst throughput of micro-batching vs the naive
               one-request-per-dispatch server (the >=2x claim), a
               Poisson-arrivals pass for latency percentiles, and the
               offline sharded sweep on the identical clusters as the
               throughput ceiling / bit-identity reference, plus a
               chaos pass: Poisson load under injected faults (ladder
               retries, one worker-killing crash) reporting
               availability, p99, and restart counts (--serve-n
               overrides the request count for smoke runs; slow-only
               in CI)
  --precision  f32 vs bf16 band store (params.band_dtype) on the
               headline and ref-default configs: seconds, modeled
               band/total byte reduction at the 1 kb x 256 fused-step
               shape, pct_hbm_roof when dispatches record, and the
               consensus-identity + template-recovery gates
               (--precision-timed overrides the timed-run count)
  --multichip  mesh scale-out: the north-star consensus with its read
               axis sharded over 1/2/4/8-device meshes (wall, identity
               vs the unsharded oracle, modeled ICI-aware efficiency)
               plus the per-device executor fleet's requests/sec/chip
               on a heterogeneous stream; prints one "MULTICHIP {...}"
               JSON line (--multichip-reads/-len/-timed/-serve-n
               override for smoke runs)
  --quick      headline only (skip the north-star / ref-default extras)
"""

import json
import os
import sys
import time

import numpy as np

# CPU-backend wall times of the IDENTICAL e2e runs on the dev host
# (python bench.py --cpu; see BASELINE.md). Backend verified "cpu" (the
# env var alone silently keeps the TPU — see --cpu). The date/commit ride
# along in the JSON so a stale baseline is detectable.
CPU_E2E_SECONDS = 19.09  # headline: 1 kb x 256, full batch, all-edits
CPU_NORTHSTAR_SECONDS = 369.0  # 2048 x 1 kb (round-3 measurement)
# ref-default (fixed top-5 INIT batch, batch 20, alignment proposals):
# the CPU *wins* this config (0.38 s vs ~1.0 s TPU). NOT a dispatch-
# amortization story: the device loop runs whole stages in one launch,
# yet the 5-20 read batches fill <= 16% of the fused step's 128-lane
# axis (the `lane_occupancy` field rides along in the JSON), so every
# step pays the PADDED shape's bytes — utils.roofline fused_mega_model
# at Npad=128 — for a sliver of useful lanes. Cross-request lane
# packing (serve batcher / sweep lane_target) is the remedy; a solo
# run has nothing to pack with, and the full-batch headline config is
# the TPU-native operating point. Reported honestly either way.
# Re-measured 2026-08-08 on the round-7 container (runs 0.438/0.487 s;
# round-5 dev host recorded 0.381 s).
CPU_REF_DEFAULT_SECONDS = 0.438
CPU_BASELINE_META = {"date": "2026-07-30", "commit": "round-5"}
# CPU-backend fused-step time for --step mode (round-2 measurement).
CPU_BASELINE_STEP_SECONDS = 1.294

TLEN = 1000
N_READS = 256
N_TIMED = 5


def build_e2e_problem(tlen=TLEN, n_reads=N_READS, seed=0, error_rate=0.01):
    from rifraf_tpu.models.errormodel import ErrorModel
    from rifraf_tpu.sim.sample import sample_sequences

    rng = np.random.default_rng(seed)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=n_reads, length=tlen, error_rate=error_rate, rng=rng,
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    return template, seqs, phreds


def run_e2e(seqs, phreds, bandwidth=None, max_iters=100, ref_default=False,
            device_loop=None, do_score=False, band_dtype=None,
            input_enc=None, speculate_k=None):
    """One full consensus; returns (wall_seconds, result)."""
    from rifraf_tpu.engine.driver import rifraf
    from rifraf_tpu.engine.params import RifrafParams

    if ref_default:
        # the REFERENCE-DEFAULT parameter set (model.jl:97-164 defaults:
        # fixed top-5 INIT batch, batch_size 20 with growth, alignment-
        # derived candidates) — the algorithm cli/consensus.py runs on
        # real data; identical on both backends
        kw = {}
    else:
        # The TPU-native full-batch configuration, identical on BOTH
        # backends so vs_baseline compares execution strategy, not
        # algorithm:
        # - no subsampling / no fixed top-k INIT batch: every iteration
        #   fills and rescores ALL reads (with defaults, a no-reference
        #   run stays in INIT on the top-batch_fixed_size reads only —
        #   that would benchmark 5-read fills regardless of n_reads);
        # - do_alignment_proposals=False: candidates come from the dense
        #   all-edits tables (which both backends compute anyway)
        #   instead of traceback-restricted sets — this is what makes
        #   the stage loop device-resident (engine.device_loop, 'auto'
        #   engages it on TPU; on CPU the same algorithm runs in the
        #   host loop).
        kw = {"batch_size": 0, "batch_fixed": False,
              "do_alignment_proposals": False}
    if bandwidth is not None:
        kw["bandwidth"] = bandwidth
    if device_loop is not None:
        kw["device_loop"] = device_loop
    if do_score:
        kw["do_score"] = True
    if band_dtype is not None:
        kw["band_dtype"] = band_dtype
    if input_enc is not None:
        kw["input_enc"] = input_enc
    if speculate_k is not None:
        kw["speculate_k"] = speculate_k
    params = RifrafParams(max_iters=max_iters, **kw)
    t0 = time.perf_counter()
    result = rifraf(seqs, phreds=phreds, params=params)
    return time.perf_counter() - t0, result


def measure_e2e(tlen=TLEN, n_reads=N_READS, bandwidth=None, n_timed=N_TIMED,
                max_iters=100, verbose=False, ref_default=False,
                device_loop=None, do_score=False, band_dtype=None,
                input_enc=None, speculate_k=None):
    template, seqs, phreds = build_e2e_problem(tlen, n_reads)
    walls = []
    result = None
    for i in range(n_timed + 1):  # first run compiles
        wall, result = run_e2e(seqs, phreds, bandwidth=bandwidth,
                               max_iters=max_iters, ref_default=ref_default,
                               device_loop=device_loop, do_score=do_score,
                               band_dtype=band_dtype, input_enc=input_enc,
                               speculate_k=speculate_k)
        if verbose:
            label = "compile+run" if i == 0 else "warm"
            print(f"  run {i}: {wall:.2f}s ({label})", file=sys.stderr)
        if i > 0:
            walls.append(wall)
    n_iters = int(result.state.stage_iterations.sum())
    recovered = bool(np.array_equal(result.consensus, template))
    return walls, n_iters, recovered, result


def speculation_block(tlen=TLEN, n_reads=N_READS, n_timed=1,
                      verbose=False, ref_default=False,
                      device_loop="on", speculate_k=2):
    """Serial vs speculative refine rounds on the same problem: runs
    the config with speculate_k=0 and with ``speculate_k``, asserts the
    consensus is identical (speculation is result-invariant by
    construction — a hit replays the exact serial choice, a miss falls
    back), and reports the round counts, hit rate, and wall seconds of
    both legs. device_loop="on" because speculation lives in the
    device-resident stage loop (engine.device_loop)."""
    walls0, it0, _, res0 = measure_e2e(
        tlen=tlen, n_reads=n_reads, n_timed=n_timed, verbose=verbose,
        ref_default=ref_default, device_loop=device_loop, speculate_k=0)
    walls_s, it_s, _, res_s = measure_e2e(
        tlen=tlen, n_reads=n_reads, n_timed=n_timed, verbose=verbose,
        ref_default=ref_default, device_loop=device_loop,
        speculate_k=speculate_k)
    m = res_s.metadata.get("speculation") or {}
    rounds = sum(s["rounds"] for s in m.get("stages", {}).values())
    rounds = rounds or it_s
    return {
        "speculate_k": speculate_k,
        "serial_iterations": it0,
        "speculative_rounds": rounds,
        "round_reduction": round(it0 / max(rounds, 1), 2),
        "attempts": m.get("attempts", 0),
        "hits": m.get("hits", 0),
        "hit_rate": m.get("hit_rate"),
        "serial_s": round(min(walls0), 3),
        "speculative_s": round(min(walls_s), 3),
        "consensus_identical": bool(
            np.array_equal(res0.consensus, res_s.consensus)),
    }


# the device round-trip sections of Timers.data: every host-loop
# iteration pays these once or more (the device-resident stage loop
# replaces them with one dispatch + one fetch per STAGE)
_DISPATCH_TIMERS = ("fused_dispatch", "packed_fetch", "moves_fetch",
                    "adapt_dispatch", "adapt_fetch")


def roofline_stats(result):
    """Measured fraction of the HBM roof for a finished run's fused
    Pallas dispatches: modelled bytes-moved per dispatch (the block
    planner records one utils.roofline entry per fused_step call)
    against the host-observed dispatch + packed-fetch wall time of the
    same sections. None when the run made no recorded Pallas dispatches
    (CPU/XLA backend, or a fully device-resident stage loop)."""
    from rifraf_tpu.utils import roofline

    recs = [r for r in roofline.snapshot() if r["kernel"] == "fused_step"]
    data = result.timers.data
    if not recs or "fused_dispatch" not in data:
        return None
    calls, seconds = data["fused_dispatch"]
    seconds += data.get("packed_fetch", (0, 0.0))[1]
    mean_bytes = sum(r["model_bytes"] for r in recs) / len(recs)
    per_dispatch = seconds / max(calls, 1)
    u = roofline.utilization(mean_bytes, per_dispatch)
    r = recs[-1]
    return {
        "dispatches": calls,
        "model_gb_per_dispatch": round(mean_bytes / 1e9, 3),
        "seconds_per_dispatch": round(per_dispatch, 4),
        "gbps": round(u["gbps"], 1),
        "pct_hbm_roof": round(u["pct_hbm"], 1),
        "hbm_roof_gbps": roofline.HBM_GBPS,
        "plan": {"T1p": r["T1p"], "K": r["K"], "C": r["C"],
                 "Npad": r["Npad"],
                 "band_dtype": r.get("band_dtype", "f32")},
    }


def ref_default_lane_stats():
    """Lane-occupancy read-back for a just-finished ref-default run: the
    device-loop stage runners record one fused_step entry per compiled
    shape with the batch's live-lane / padded-lane ratio (a 5-read INIT
    batch fills 5/128 of the lane axis — the honest reason the CPU won
    this config before segment-pair packing doubled the fill; see
    CPU_REF_DEFAULT_SECONDS). Both Pallas and XLA stage runners record
    (engine.realign), so the block reaches the BENCH JSON on every
    backend. ``model_gb_effective`` discounts the padded-shape byte
    model by the lane occupancy — the bytes spent on live lanes. None
    when no stage runner was engaged (pure host loop)."""
    from rifraf_tpu.utils import roofline

    recs = [r for r in roofline.snapshot()
            if r["kernel"] == "fused_step" and r.get("lane_occupancy")]
    if not recs:
        return None
    occ = min(r["lane_occupancy"] for r in recs)
    gb = sum(r["model_bytes"] for r in recs) / len(recs) / 1e9
    return {
        "lane_occupancy": round(occ, 4),
        # the ref-default batch has no cluster-block padding (every live
        # lane carries a real read), so read granularity matches
        "lane_occupancy_reads": round(occ, 4),
        "model_gb_per_dispatch": round(gb, 3),
        "model_gb_effective": round(gb * occ, 3),
        "impl": recs[-1]["impl"],
    }


def _with_segment_pack(value, fn):
    """Run ``fn`` with RIFRAF_TPU_SEGMENT_PACK pinned (the packed vs
    unpacked stage-batch comparison), restoring the prior setting."""
    old = os.environ.get("RIFRAF_TPU_SEGMENT_PACK")
    os.environ["RIFRAF_TPU_SEGMENT_PACK"] = value
    try:
        return fn()
    finally:
        if old is None:
            os.environ.pop("RIFRAF_TPU_SEGMENT_PACK", None)
        else:
            os.environ["RIFRAF_TPU_SEGMENT_PACK"] = old


def host_dispatch_stats(result, walls):
    """Per-iteration host-dispatch latency of a finished run: wall time
    and device round-trip seconds (dispatch + fetch timer sections)
    divided by the hill-climb iteration count."""
    n_iters = max(int(result.state.stage_iterations.sum()), 1)
    data = result.timers.data
    dispatch_s = sum(data[k][1] for k in _DISPATCH_TIMERS if k in data)
    wall = min(walls)
    return {
        "iterations": n_iters,
        "wall_per_iter_ms": round(wall / n_iters * 1000, 2),
        "dispatch_per_iter_ms": round(dispatch_s / n_iters * 1000, 2),
        "dispatch_seconds": round(dispatch_s, 3),
    }


def _step_mode():
    """Round-2 fused-step microbenchmark (kept for comparability)."""
    import jax
    import jax.numpy as jnp

    from rifraf_tpu.models.errormodel import ErrorModel, Scores
    from rifraf_tpu.models.sequences import batch_reads, make_read_scores
    from rifraf_tpu.ops import align_jax
    from rifraf_tpu.ops.fused import fused_step

    scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
    rng = np.random.default_rng(0)
    # template drawn FIRST: the round-2 CPU baseline constant was measured
    # on this exact RNG stream, so draw order is part of the comparison
    template = rng.integers(0, 4, size=TLEN).astype(np.int8)
    reads = []
    for _ in range(N_READS):
        slen = int(rng.integers(950, 1050))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, 16, scores))
    batch = batch_reads(reads, dtype=np.float32)
    K = align_jax.band_height(batch, TLEN)
    geom = align_jax.batch_geometry(batch, TLEN)
    t_dev = jnp.asarray(np.pad(template, (0, 24)), jnp.int8)
    w = jnp.ones(N_READS, jnp.float32)
    base_match = np.asarray(batch.match)
    seq_d = jnp.asarray(batch.seq)
    mm_d = jnp.asarray(batch.mismatch)
    ins_d = jnp.asarray(batch.ins)
    dels_d = jnp.asarray(batch.dels)

    def run(i):
        # distinct content per timed call defeats any result reuse
        m = jnp.asarray(base_match * (1.0 + 1e-6 * i))
        jax.block_until_ready(m)
        t0 = time.perf_counter()
        r = fused_step(t_dev, seq_d, m, mm_d, ins_d, dels_d, geom, w, K)
        jax.block_until_ready(r)
        return time.perf_counter() - t0

    run(0)  # compile
    dt = min(run(i + 1) for i in range(5))
    P = 4 * TLEN + 4 * (TLEN + 1) + TLEN
    value = N_READS * P / dt
    baseline_value = N_READS * P / CPU_BASELINE_STEP_SECONDS
    print(json.dumps({
        "metric": "proposal_scores_per_sec_1kb_256reads_fused",
        "value": round(value, 1),
        "unit": "proposal-scores/s",
        "vs_baseline": round(value / baseline_value, 2),
    }))


def _northstar_mode():
    """The BASELINE.json north-star configs, end to end."""
    import jax

    backend = jax.default_backend()
    for label, tlen, n_reads, bandwidth, n_timed in (
        ("2048x1kb", 1000, 2048, None, 2),
        ("10kbx512_band64", 10000, 512, 64, 1),
    ):
        from rifraf_tpu.utils import roofline as _roofline

        _roofline.clear()
        walls, n_iters, recovered, res = measure_e2e(
            tlen, n_reads, bandwidth=bandwidth, n_timed=n_timed, verbose=True
        )
        wall = min(walls)
        print(json.dumps({
            "config": label,
            "backend": backend,
            "e2e_seconds": round(wall, 3),
            "runs_s": [round(w, 3) for w in walls],
            "iterations": n_iters,
            "seconds_per_iteration": round(wall / max(n_iters, 1), 4),
            "template_recovered": recovered,
            "roofline": roofline_stats(res),
            # the banded 10 kb config read-chunks the speculative
            # launch's duplicated reads, so only the 2048x1kb leg
            # measures speculation
            "speculation": (speculation_block(tlen=tlen,
                                              n_reads=n_reads,
                                              n_timed=1)
                            if label == "2048x1kb" else None),
        }))


def _golden_mode():
    """Shipped-data CLI run (the reference notebook's 3.6 s anchor)."""
    import os
    import tempfile

    from rifraf_tpu.cli.consensus import main as consensus_main

    data = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
    argv = [
        "--reference", os.path.join(data, "references.fasta"),
        "--reference-map", os.path.join(data, "ref-map.tsv"),
        "--phred-cap", "30",
        "1,2,2",
        os.path.join(data, "input-reads-*.fastq"),
    ]
    walls = []
    for _ in range(3):
        with tempfile.NamedTemporaryFile(suffix=".fasta") as out:
            t0 = time.perf_counter()
            rc = consensus_main(argv + [out.name])
            walls.append(time.perf_counter() - t0)
            assert rc == 0
    print(json.dumps({
        "config": "shipped_golden_cli_2clusters",
        "warm_seconds": round(min(walls), 3),
        "cold_seconds": round(walls[0], 3),
        "reference_anchor_seconds": 3.6,
    }))


def _sweep_roofline(plans, results, seconds):
    """Model-based HBM-roof fraction for a sweep: per chunk, the fused-
    step byte model at the bucket's padded shape (lane-slot Npad — the
    [gp, N] read axes flattened onto 128-lane tiles) times the chunk's
    stage-step count. The step count is the max member iteration count
    (the vmapped while_loop runs until the chunk's last cluster
    converges); adaptation rounds are excluded, so the byte total is a
    floor and the pct a floor too."""
    from rifraf_tpu.parallel.sweep_sharded import (
        SegmentBucketPlan,
        _lane_slots,
    )
    from rifraf_tpu.utils import roofline
    from rifraf_tpu.utils.shapes import plan_cols

    total = 0.0
    for p in plans:
        N, _, Tmax, K0 = p.key
        C = plan_cols(Tmax, K0, kernel="dense").cols
        per_step = roofline.fused_model(
            Tmax, K0, _lane_slots(p.gp, N), C
        )["bytes"]
        for ch in p.chunks:
            # a segment-packed chunk's members sit inside PackPlans
            members = (
                [m[0] for pk in ch for m in pk.members]
                if isinstance(p, SegmentBucketPlan) else ch
            )
            steps = max((results[ci].n_iters for ci in members), default=0)
            total += per_step * steps
    u = roofline.utilization(total, seconds)
    return {
        "model_gb": round(total / 1e9, 3),
        "gbps": round(u["gbps"], 1),
        "pct_hbm_roof": round(u["pct_hbm"], 2),
        "hbm_roof_gbps": roofline.HBM_GBPS,
    }


def _precision_mode():
    """f32 vs bf16 band store on the same seeded problems: wall
    seconds, the MODELED band-byte reduction (deterministic — the
    dtype lever is a byte-wall story, so the roofline model is the
    honest metric on any backend), measured pct_hbm_roof when Pallas
    dispatches record, and the consensus gates: planted-template
    recovery at both precisions plus bf16 == f32 consensus identity.
    Covers the headline 1 kb x 256 full-batch config and
    ref_default_1kb_256 (--precision-timed overrides the timed-run
    count for smoke runs)."""
    import jax

    from rifraf_tpu.utils import roofline

    n_timed = 2
    if "--precision-timed" in sys.argv:
        n_timed = int(sys.argv[sys.argv.index("--precision-timed") + 1])

    out = {"config": "precision_f32_vs_bf16",
           "backend": jax.default_backend()}
    shape = None
    for name, kw in (
        ("e2e_1kb_256", {}),
        ("ref_default_1kb_256", {"ref_default": True}),
    ):
        block = {}
        cons = {}
        for bd in ("f32", "bf16"):
            roofline.clear()
            walls, n_iters, recovered, result = measure_e2e(
                n_timed=n_timed, band_dtype=bd, **kw)
            cons[bd] = result.consensus.tolist()
            block[bd] = {
                "seconds": round(min(walls), 3),
                "runs_s": [round(w, 3) for w in walls],
                "n_iters": n_iters,
                "recovered": recovered,
            }
            rl = roofline_stats(result)
            if rl:
                block[bd]["pct_hbm_roof"] = rl["pct_hbm_roof"]
                block[bd]["model_gb_per_dispatch"] = (
                    rl["model_gb_per_dispatch"]
                )
            if name == "e2e_1kb_256":
                recs = [r for r in roofline.snapshot()
                        if r["kernel"] == "fused_step"]
                if recs:
                    r = recs[-1]
                    shape = (r["T1p"], r["K"], r["C"], r["Npad"])
        block["consensus_identical"] = cons["f32"] == cons["bf16"]
        block["bf16_speedup"] = round(
            block["f32"]["seconds"] / block["bf16"]["seconds"], 2
        )
        out[name] = block

    # modeled byte reduction at the 1 kb x 256 fused-step shape (from
    # the recorded dispatch when the run routed through a recording
    # path, else the config's canonical plan) — independent of backend
    # and timer noise. Band terms halve (2 bytes vs 4); tables, tiles,
    # and move codes stay f32/int32, so the TOTAL reduction reports how
    # band-dominated the shape actually is.
    if shape is None:
        from rifraf_tpu.utils.shapes import plan_cols

        T1p, K, Npad = 1024, 64, 256
        C = plan_cols(T1p, K, "fill").cols
        shape = (T1p, K, C, Npad)
    T1p, K, C, Npad = shape
    m = {
        isz: roofline.fused_mega_model(T1p, K, Npad, C,
                                       band_itemsize=isz)
        for isz in (4, 2)
    }
    out["model_shape"] = {"T1p": T1p, "K": K, "C": C, "Npad": Npad}
    out["modeled_band_byte_reduction"] = round(
        1.0 - m[2]["band_bytes"] / m[4]["band_bytes"], 4
    )
    out["modeled_total_byte_reduction"] = round(
        1.0 - m[2]["bytes"] / m[4]["bytes"], 4
    )

    # --- input encoding (params.input_enc) at the same shape: the full
    # band_dtype x input_enc matrix of modeled fused-step bytes, so the
    # two byte levers are reported separately AND combined. "packed"
    # shrinks only the streamed input tables (2-bit bases + int8 score
    # planes, ops.encoding): modeled_input_byte_reduction is the table-
    # term reduction (the honest per-lever number); the per-cell
    # total_reduction values show how much of the whole step each
    # combination removes — the packed+bf16 cell is the headline, since
    # the two levers cut disjoint byte terms.
    enc_m = {
        (isz, enc): roofline.fused_mega_model(
            T1p, K, Npad, C, band_itemsize=isz, input_enc=enc)
        for isz in (4, 2) for enc in ("f32", "packed")
    }
    base = enc_m[(4, "f32")]
    out["input_encoding"] = {
        "modeled_input_byte_reduction": round(
            1.0 - enc_m[(4, "packed")]["tab_bytes"] / base["tab_bytes"],
            4,
        ),
        "input_tab_fraction_of_step": round(
            base["tab_bytes"] / base["bytes"], 4
        ),
        "matrix": {
            f"band_{'f32' if isz == 4 else 'bf16'}_input_{enc}": {
                "model_gb": round(mm["bytes"] / 1e9, 4),
                "total_reduction_vs_f32_f32": round(
                    1.0 - mm["bytes"] / base["bytes"], 4
                ),
            }
            for (isz, enc), mm in enc_m.items()
        },
        # headline: both levers on (disjoint terms: bands vs tables)
        "modeled_combined_byte_reduction": round(
            1.0 - enc_m[(2, "packed")]["bytes"] / base["bytes"], 4
        ),
    }
    print(json.dumps(out))


def _sweep_mode():
    """Heterogeneous multi-cluster sweep: bucketed vs uniform scheduler
    (parallel.sweep_sharded), same inputs, bit-identical results; plus
    the adaptive band-growth policy vs the doubling reference on a
    length-proportional-bandwidth rebuild of the same reads (settled
    band mass, consensus identity)."""
    import jax

    from rifraf_tpu.engine.params import RifrafParams
    from rifraf_tpu.models.errormodel import ErrorModel
    from rifraf_tpu.models.sequences import make_read_scores
    from rifraf_tpu.parallel.sharding import make_mesh
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded
    from rifraf_tpu.sim.sample import sample_sequences
    from rifraf_tpu.utils.phred import phred_to_log_p

    n_clusters = 2048
    if "--sweep-n" in sys.argv:
        n_clusters = int(sys.argv[sys.argv.index("--sweep-n") + 1])
    chunk = 256
    if "--sweep-chunk" in sys.argv:
        chunk = int(sys.argv[sys.argv.index("--sweep-chunk") + 1])

    rng = np.random.default_rng(12)
    params = RifrafParams()
    seq_errors = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)
    clusters = []
    raw = []  # (seq, error_log_p) pairs per cluster, for rebuilds below
    for _ in range(n_clusters):
        # log-normal template lengths and ragged cluster sizes: the
        # realistic amplicon mix whose pad-to-global-maxima cost the
        # bucketed scheduler exists to avoid
        tlen = int(np.clip(rng.lognormal(np.log(250), 0.5), 60, 1500))
        nseqs = int(rng.integers(3, 13))
        _, _, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=nseqs, length=tlen, error_rate=0.02, rng=rng,
            seq_errors=seq_errors,
        )
        raw.append([
            (s, phred_to_log_p(np.asarray(p, float)))
            for s, p in zip(seqs, phreds)
        ])
        clusters.append([
            make_read_scores(s, lp, params.bandwidth, params.scores)
            for s, lp in raw[-1]
        ])

    mesh = make_mesh() if len(jax.devices()) > 1 else None
    out = {
        "config": f"sweep_het_{n_clusters}",
        "backend": jax.default_backend(),
        "n_clusters": n_clusters,
        "cluster_chunk": chunk,
    }
    results = {}
    for sched in ("bucketed", "uniform"):
        # warm-up compiles every shape signature; the timed run reuses
        # the cached executables (the production steady state)
        sweep_clusters_sharded(clusters, mesh=mesh, cluster_chunk=chunk,
                               scheduler=sched)
        res, stats = sweep_clusters_sharded(
            clusters, mesh=mesh, cluster_chunk=chunk, scheduler=sched,
            return_stats=True,
        )
        results[sched] = res
        out[f"{sched}_seconds"] = round(stats.seconds, 3)
        out[f"{sched}_waste"] = round(stats.waste, 4)
        if sched == "bucketed":
            out["n_buckets"] = stats.n_buckets
            # executed lane packing (plan_sweep lane_target floor +
            # underfilled-bucket coalescing): slot fill = real clusters'
            # Npad blocks over the 128-lane slots the launches occupied;
            # the _reads variant further discounts within-cluster
            # padding to Npad (bounded by the read-count grid)
            out["lane_occupancy"] = round(stats.lane_occupancy, 4)
            out["lane_occupancy_reads"] = round(
                stats.lane_occupancy_reads, 4
            )
            from rifraf_tpu.parallel.sweep_sharded import (
                _cluster_infos,
                plan_sweep,
            )

            plans = plan_sweep(clusters, cluster_chunk=chunk,
                               infos=_cluster_infos(clusters))
            out["roofline"] = _sweep_roofline(plans, res, stats.seconds)
            out["pct_hbm_roof"] = out["roofline"]["pct_hbm_roof"]
    out["speedup"] = round(
        out["uniform_seconds"] / out["bucketed_seconds"], 2
    )
    out["results_identical"] = all(
        np.array_equal(a.consensus, b.consensus) and a.score == b.score
        for a, b in zip(results["bucketed"], results["uniform"])
    )

    # ---- adaptive band growth vs the doubling reference ----
    # Rebuild the same reads with a length-proportional caller
    # bandwidth (max(default, len/10) — the conservative default of a
    # caller that does not know its error rate): the configuration the
    # adaptive policy exists for. Adaptive enters at min(bw, 16) and
    # grows only wall-riding reads by their measured deficit, so its
    # settled band mass should sit well under doubling's; consensus
    # must be identical.
    bw_clusters = [
        [make_read_scores(s, lp, max(params.bandwidth, len(s) // 10),
                          params.scores)
         for s, lp in c]
        for c in raw
    ]

    def _mean_bw(hist):
        tot = sum(cnt for _, cnt in hist)
        return (
            sum(b * cnt for b, cnt in hist) / tot if tot else 0.0
        )

    growth_res = {}
    for bg in ("double", "adaptive"):
        sweep_clusters_sharded(bw_clusters, mesh=mesh,
                               cluster_chunk=chunk, band_growth=bg)
        res_g, stats_g = sweep_clusters_sharded(
            bw_clusters, mesh=mesh, cluster_chunk=chunk, band_growth=bg,
            return_stats=True,
        )
        growth_res[bg] = res_g
        out[f"{bg}_growth_seconds"] = round(stats_g.seconds, 3)
        out[f"{bg}_mean_bw"] = round(_mean_bw(stats_g.bw_hist), 2)
    out["adaptive_bw_ratio"] = round(
        out["adaptive_mean_bw"] / out["double_mean_bw"], 3
    ) if out["double_mean_bw"] else 1.0
    out["adaptive_results_identical"] = all(
        np.array_equal(a.consensus, b.consensus)
        for a, b in zip(growth_res["adaptive"], growth_res["double"])
    )
    print(json.dumps(out))


def _serve_workload(n_requests, rng):
    """Heterogeneous serving workload: log-normal template lengths and
    ragged cluster sizes (the --sweep distribution, so the serve numbers
    are comparable to the offline sweep's)."""
    from rifraf_tpu.engine.params import RifrafParams
    from rifraf_tpu.models.errormodel import ErrorModel
    from rifraf_tpu.models.sequences import make_read_scores
    from rifraf_tpu.sim.sample import sample_sequences
    from rifraf_tpu.utils.phred import phred_to_log_p

    params = RifrafParams()
    seq_errors = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)
    clusters = []
    for _ in range(n_requests):
        tlen = int(np.clip(rng.lognormal(np.log(250), 0.5), 60, 1500))
        nseqs = int(rng.integers(3, 13))
        _, _, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=nseqs, length=tlen, error_rate=0.02, rng=rng,
            seq_errors=seq_errors,
        )
        clusters.append([
            make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                             params.bandwidth, params.scores)
            for s, p in zip(seqs, phreds)
        ])
    return clusters


def _serve_burst(clusters, config):
    """submit_many as fast as the backpressure allows; returns
    (throughput_rps, responses, stats_snapshot)."""
    from rifraf_tpu.serve import ConsensusServer, submit_many

    server = ConsensusServer(config)
    try:
        server.warmup(clusters, batch_sizes=(1, config.max_batch))
        t0 = time.perf_counter()
        responses = submit_many(clusters, server=server)
        wall = time.perf_counter() - t0
        snap = server.snapshot()
    finally:
        server.close()
    assert all(r.ok for r in responses)
    return len(clusters) / wall, responses, snap


def _serve_mode():
    """serve_poisson_1k: online service vs naive dispatch vs offline
    sweep on the identical heterogeneous workload."""
    import jax

    from rifraf_tpu.parallel.sharding import make_mesh
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded
    from rifraf_tpu.serve import ConsensusServer, ServeConfig

    n_requests = 1000
    if "--serve-n" in sys.argv:
        n_requests = int(sys.argv[sys.argv.index("--serve-n") + 1])
    max_batch = 16
    if "--serve-batch" in sys.argv:
        max_batch = int(sys.argv[sys.argv.index("--serve-batch") + 1])
    # a full pass re-traces the bucket grid in nearly every section and
    # runs ~25 min on a CPU host; --serve-sections 3,7 (for example)
    # runs a subset. Sections 5 and 7 compare against the offline
    # reference, so selecting them pulls section 3 in.
    sections = set(range(1, 8))
    if "--serve-sections" in sys.argv:
        sections = {
            int(s) for s in
            sys.argv[sys.argv.index("--serve-sections") + 1].split(",")
        }
        if sections & {5, 7}:
            sections.add(3)

    rng = np.random.default_rng(12)
    clusters = _serve_workload(n_requests, rng)
    mesh = make_mesh() if len(jax.devices()) > 1 else None

    # section progress on stderr: the full pass is many minutes of
    # compile-dominated wall time, and a truncated run should say where
    # it died
    t_mode0 = time.perf_counter()

    def _mark(msg):
        print(f"[serve] +{time.perf_counter() - t_mode0:.1f}s {msg}",
              file=sys.stderr, flush=True)

    # cross-section values with skip-safe defaults
    rps_batched, responses = 0.0, None
    lam = 1.0
    n_chaos = min(n_requests, 200)
    chaos_clusters = clusters[:n_chaos]

    out = {
        "config": f"serve_poisson_{n_requests}",
        "backend": jax.default_backend(),
        "n_requests": n_requests,
    }
    if sections != set(range(1, 8)):
        out["sections"] = sorted(sections)

    if 1 in sections:
        _mark("1: burst throughput")
        # 1. burst throughput: micro-batched vs naive one-request-per-
        # dispatch (max_batch=1 — every request is its own device program
        # invocation, the no-batcher strawman)
        batched_cfg = ServeConfig(max_wait_ms=5.0, max_batch=max_batch,
                                  mesh=mesh)
        naive_cfg = ServeConfig(max_batch=1, mesh=mesh)
        rps_batched, responses, snap = _serve_burst(clusters, batched_cfg)
        rps_naive, _, _ = _serve_burst(clusters, naive_cfg)
        out["throughput_rps"] = round(rps_batched, 2)
        out["naive_rps"] = round(rps_naive, 2)
        out["speedup_vs_naive"] = round(rps_batched / rps_naive, 2)
        out["batch_occupancy"] = snap["batch_occupancy"]
        out["padding_waste"] = snap["padding_waste"]
        out["batches"] = snap["batches"]
        # executed lane packing of the dispatched micro-batches, and the
        # model-based HBM-roof fraction over the dispatch+fetch sections
        out["lane_occupancy"] = snap["lane_occupancy"]
        out["lane_occupancy_reads"] = snap["lane_occupancy_reads"]
        from rifraf_tpu.utils import roofline as _roofline

        td = snap["timers"]
        secs = sum(td[k]["seconds"]
                   for k in ("serve_dispatch", "serve_fetch") if k in td)
        u = _roofline.utilization(snap["model_gb"] * 1e9, secs)
        out["model_gb"] = snap["model_gb"]
        out["pct_hbm_roof"] = round(u["pct_hbm"], 2)

    if 2 in sections:
        _mark("2: poisson latency")
        # 2. Poisson arrivals at half the measured burst throughput: the
        # open-loop latency the service shows with steady-state headroom
        lam = max(rps_batched * 0.5, 1.0)
        out["poisson_rate_rps"] = round(lam, 2)
        from rifraf_tpu.serve import QueueFullError

        server = ConsensusServer(ServeConfig(max_wait_ms=5.0,
                                             max_batch=max_batch, mesh=mesh))
        try:
            server.warmup(clusters, batch_sizes=(1, batched_cfg.max_batch))
            futures = []
            for c in clusters:
                while True:
                    try:
                        futures.append(server.submit(c))
                        break
                    except QueueFullError:
                        # open-loop overload: wait out the oldest in flight
                        futures[0].result()
                time.sleep(rng.exponential(1.0 / lam))
            for f in futures:
                f.result()
            psnap = server.snapshot()
        finally:
            server.close()
        out["latency_ms"] = psnap["latency_ms"]
        out["timers"] = psnap["timers"]

    if 3 in sections:
        _mark("3: offline sweep")
        # 3. offline sharded sweep on the SAME clusters: the batch-mode
        # throughput ceiling, and the bit-identity reference for the served
        # results
        sweep_clusters_sharded(clusters, mesh=mesh)  # warm-up compiles
        t0 = time.perf_counter()
        offline, _ = sweep_clusters_sharded(clusters, mesh=mesh,
                                            return_stats=True)
        offline_wall = time.perf_counter() - t0
        out["offline_sweep_rps"] = round(n_requests / offline_wall, 2)
        if responses is not None:
            out["results_match_offline"] = all(
                np.array_equal(r.consensus, o.consensus)
                and r.score == o.score
                for r, o in zip(responses, offline)
            )

    if 4 in sections:
        _mark("4: chaos")
        # 4. chaos: Poisson arrivals under injected faults — transient
        # dispatch errors (the degradation ladder re-runs those
        # micro-batches one rung down), slowed fetches, and one
        # worker-killing crash mid-run (the supervisor restarts the thread
        # and requeues its in-flight requests). Availability is the
        # fraction of requests answered ok; every future must resolve
        # typed — the acceptance bar is availability >= 0.99 with at least
        # one worker restart.
        faults = ("dispatch:error:n=2;fetch:delay:ms=20,n=5;"
                  f"dispatch:crash:after={max(3, n_chaos // 20)},n=1")
        chaos_cfg = ServeConfig(max_wait_ms=5.0, max_batch=max_batch,
                                mesh=mesh, faults=faults,
                                restart_backoff_s=0.01,
                                supervise_interval_s=0.02,
                                result_timeout_s=120.0)
        server = ConsensusServer(chaos_cfg)
        try:
            server.warmup(chaos_clusters, batch_sizes=(1, max_batch))
            futures = []
            for c in chaos_clusters:
                while True:
                    try:
                        futures.append(server.submit(c))
                        break
                    except QueueFullError:
                        futures[0].result()
                time.sleep(rng.exponential(1.0 / lam))
            chaos_responses = [
                f.result(timeout=chaos_cfg.result_timeout_s)
                for f in futures
            ]
            health = server.health()
            csnap = server.snapshot()
            server_stats_integrity = server.stats.integrity()
        finally:
            server.close()
        n_ok = sum(r.ok for r in chaos_responses)
        out["chaos"] = {
            "n_requests": n_chaos,
            # the ACTIVE fault-plan string + integrity counters ride the
            # BENCH line so a chaos run is reproducible from the artifact
            # alone (replay the same spec, compare the same counters)
            "fault_plan": faults,
            "faults": faults,
            "integrity_counters": server_stats_integrity,
            "availability": round(n_ok / n_chaos, 4),
            "all_resolved_typed": all(
                r.ok or r.error is not None for r in chaos_responses
            ),
            "p99_ms": csnap["latency_ms"].get("p99"),
            "worker_restarts": health["worker_restarts"],
            "retry_ladder": health["retry_ladder"],
        }

    if 5 in sections:
        _mark("5: integrity")
        # 5. result integrity under fire: the `corrupt` fault kind flips a
        # float64 bit on fetched scores — a SILENT wrong answer that no
        # crash supervision can see. With verify_fraction=1.0 + guard
        # sentinels on, every corruption must be detected by shadow
        # verification (oracle re-score on the independent fused-impl
        # path), the oracle result must replace the bad answer (so
        # availability stays >= 0.99 — answers are corrected, not
        # refused), and the poisoned device must land on the quarantine
        # scoreboard.
        n_corrupt = max(3, n_chaos // 20)
        int_faults = f"fetch:corrupt:n={n_corrupt}"
        int_cfg = ServeConfig(max_wait_ms=5.0, max_batch=max_batch,
                              mesh=mesh, faults=int_faults,
                              guard=True, verify_fraction=1.0,
                              quarantine_threshold=3,
                              result_timeout_s=120.0)
        server = ConsensusServer(int_cfg)
        try:
            server.warmup(chaos_clusters, batch_sizes=(1, max_batch))
            futures = []
            for c in chaos_clusters:
                while True:
                    try:
                        futures.append(server.submit(c))
                        break
                    except QueueFullError:
                        futures[0].result()
                time.sleep(rng.exponential(1.0 / lam))
            int_responses = [
                f.result(timeout=int_cfg.result_timeout_s)
                for f in futures
            ]
            ihealth = server.health()
        finally:
            server.close()
        ictr = ihealth["integrity"]["counters"]
        injected = ictr.get("injected_corrupt", 0)
        detected = ictr.get("verify_divergence", 0)
        n_ok = sum(r.ok for r in int_responses)
        out["integrity"] = {
            "n_requests": n_chaos,
            "fault_plan": int_faults,
            "verify_fraction": 1.0,
            "injected_corruptions": injected,
            "detected_divergences": detected,
            # the acceptance bar: 100% of injected corruptions detected
            "detection_rate": (round(detected / injected, 4)
                               if injected else None),
            "recovered": ictr.get("verify_recovered", 0),
            "availability": round(n_ok / n_chaos, 4),
            "device_quarantined": ictr.get("device_quarantined", 0) >= 1,
            "devices": ihealth["integrity"]["devices"],
            "counters": ictr,
            # every served answer — including the corrected ones — must
            # still equal the offline sweep bit-for-bit
            "results_match_offline": all(
                np.array_equal(r.consensus, o.consensus)
                and r.score == o.score
                for r, o in zip(int_responses, offline[:n_chaos])
            ),
        }

    if 6 in sections:
        _mark("6: ingest")
        # 6. ingestion durability: a synthetic malformed-FASTQ corpus pushed
        # through the io.stream front door under injected ingest faults —
        # the process must survive with every bad record quarantined with a
        # typed reason (the crash-safe ingestion acceptance bar), and the
        # quarantine accounting lands in the BENCH line next to
        # availability.
        import io as _io

        from rifraf_tpu.io.stream import QuarantineWriter, stream_fastq
        from rifraf_tpu.serve.faults import FaultPlan

        good = "@c{0}/r1\nACGTACGT\n+\nIIIIIIII\n"
        corpus = (
            "".join(good.format(i) for i in range(40))
            + "no_at_header\nACGT\n+\nIIII\n"      # bad header
            + "@bad1\nACGN\n+\nIIII\n"              # non-ACGT base
            + "@bad2\nACGT\n+\nII\n"                # qual length mismatch
            + "@bad3\nACGT\nACGT\nIIII\n"           # missing '+' line
            + "@bad4\nACGT\n+\nII I\n"              # phred below 0 (space)
            + "@tail\nACG\n"                         # truncated record
        )
        q = QuarantineWriter(None)
        ingest_faults = FaultPlan.parse("ingest:error:n=3")
        n_ingested = sum(1 for _ in stream_fastq(
            _io.StringIO(corpus), q, faults=ingest_faults,
            source="bench-corpus"))
        out["ingest"] = {
            "n_good_records": 40,
            # 3 good records eaten by the injected ingest faults
            "n_ingested": n_ingested,
            "quarantined": dict(sorted(q.counts.items())),
            "quarantine_total": q.n,
            # zero crashes (we got here) + every malformed record rejected
            # with a typed reason and no good record lost beyond the 3
            # injected faults
            "all_quarantined_typed": (
                n_ingested == 37
                and {"malformed_record", "truncated", "length_mismatch",
                     "phred_range", "bad_alphabet",
                     "injected_fault"} <= set(q.counts)
            ),
        }

    if 7 in sections:
        _mark("7: elasticity")
        # 7. elasticity + overload: (a) cold start — a warmup sweep from
        # cold program factories vs loading persisted AOT executables from
        # disk (the serve.aot tentpole; >= 5x is the acceptance bar);
        # (b) 2x Poisson overload against an elastic, shedding fleet —
        # admitted availability, typed shed rate, p99 of the admitted set,
        # the worker-count trajectory, and bit-identity of every admitted
        # answer against the fixed reference.
        import shutil
        import tempfile

        from rifraf_tpu.parallel import sweep_sharded as _ss
        from rifraf_tpu.serve import SheddedError
        from rifraf_tpu.serve import aot as _aot

        def _cold_factories():
            # simulate a fresh process: drop the lru-cached program
            # wrappers and jax's in-memory executables; only the on-disk
            # caches (persistent XLA + AOT) survive — what a cold process
            # actually sees
            _ss._adapt_program.cache_clear()
            _ss._stage_program.cache_clear()
            _ss._seg_adapt_program.cache_clear()
            _ss._seg_stage_program.cache_clear()
            jax.clear_caches()

        n_over = min(n_requests, 200)
        over_clusters = clusters[:n_over]
        aot_dir = tempfile.mkdtemp(prefix="rifraf_aot_bench_")
        warm_cfg = dict(max_wait_ms=5.0, max_batch=max_batch)
        try:
            _aot.deactivate()
            _cold_factories()
            _mark("7a: cold-start baseline (full retrace)")
            server = ConsensusServer(ServeConfig(aot_cache="off",
                                                 **warm_cfg))
            try:
                t0 = time.perf_counter()
                server.warmup(over_clusters, batch_sizes=(1, max_batch))
                t_warm_sweep = time.perf_counter() - t0
            finally:
                server.close()
            # export pass: persist the warmed grid
            _mark("7b: aot export pass")
            server = ConsensusServer(ServeConfig(aot_cache=aot_dir,
                                                 **warm_cfg))
            try:
                server.warmup(over_clusters, batch_sizes=(1, max_batch))
                aot_exported = server.aot.snapshot()
            finally:
                server.close()
            # AOT cold start: cold factories again; the same grid now loads
            # serialized executables instead of re-tracing
            _aot.deactivate()
            _cold_factories()
            _mark("7c: aot cold start")
            server = ConsensusServer(ServeConfig(aot_cache=aot_dir,
                                                 **warm_cfg))
            try:
                t0 = time.perf_counter()
                server.warmup(over_clusters, batch_sizes=(1, max_batch))
                t_aot_cold = time.perf_counter() - t0
                aot_loaded = server.aot.snapshot()
            finally:
                server.close()
            cold_start = {
                "warmup_sweep_seconds": round(t_warm_sweep, 3),
                "aot_cold_seconds": round(t_aot_cold, 3),
                "speedup": (round(t_warm_sweep / t_aot_cold, 2)
                            if t_aot_cold else None),
                "aot_exports": aot_exported["aot_exports"],
                "aot_loads": aot_loaded["aot_loads"],
                "aot_load_errors": aot_loaded["aot_load_errors"],
            }

            # (b) the overload pass: 2x the measured burst throughput into
            # an elastic shedding fleet (the AOT dir keeps ITS cold start
            # near-free too)
            _mark("7d: overload pass")
            lam2 = max(rps_batched * 2.0, 2.0)
            elastic_cfg = ServeConfig(
                max_wait_ms=5.0, max_batch=max_batch, aot_cache=aot_dir,
                min_workers=1, max_workers=3, shed=True,
                scale_up_depth=2, scale_cooldown_s=0.1,
                scale_down_idle_s=0.5,
                supervise_interval_s=0.02, result_timeout_s=120.0,
            )
            server = ConsensusServer(elastic_cfg)
            trajectory = []

            def _sample_fleet():
                h = server.health()
                n_active = h["elastic"]["active_workers"]
                if not trajectory or trajectory[-1][1] != n_active:
                    trajectory.append(
                        (round(time.perf_counter() - t_start, 3), n_active))

            try:
                server.warmup(over_clusters, batch_sizes=(1, max_batch))
                # seed the service estimator so the shed door has evidence
                # from the first arrival (an un-seeded server admits
                # everything)
                for c in over_clusters[:3]:
                    server.submit(c).result(timeout=120)
                mean_service_s = server.stats.service_estimate() or 0.05
                deadline_ms = max(1000.0, 20e3 * mean_service_s)
                t_start = time.perf_counter()
                admitted, shed_hints, n_shed = [], [], 0
                for i, c in enumerate(over_clusters):
                    try:
                        admitted.append(
                            (i, server.submit(c, deadline_ms=deadline_ms)))
                    except SheddedError as e:
                        n_shed += 1
                        shed_hints.append(e.retry_after_s)
                    except QueueFullError:
                        n_shed += 1  # hard backpressure counts as refused
                    _sample_fleet()
                    time.sleep(rng.exponential(1.0 / lam2))
                over_responses = [
                    (i, f.result(timeout=elastic_cfg.result_timeout_s))
                    for i, f in admitted
                ]
                # watch the drain back down to min_workers
                drain_deadline = time.perf_counter() + 30.0
                while time.perf_counter() < drain_deadline:
                    _sample_fleet()
                    h = server.health()
                    if (h["elastic"]["active_workers"]
                            <= h["elastic"]["min_workers"]
                            and not h["elastic"]["draining"]):
                        break
                    time.sleep(0.1)
                ehealth = server.health()
                esnap = server.snapshot()
            finally:
                server.close()
            n_admitted = len(over_responses)
            n_ok = sum(r.ok for _, r in over_responses)
            out["elastic"] = {
                "cold_start": cold_start,
                "n_requests": n_over,
                "poisson_rate_rps": round(lam2, 2),
                "deadline_ms": round(deadline_ms, 1),
                "n_admitted": n_admitted,
                "n_shed": n_shed,
                "shed_rate": round(n_shed / n_over, 4),
                "mean_retry_after_s": (
                    round(float(np.mean(shed_hints)), 3)
                    if shed_hints else None),
                # availability of the ADMITTED set: a shed request is a
                # typed refusal, not an availability miss
                "admitted_availability": (
                    round(n_ok / n_admitted, 4) if n_admitted else None),
                "all_resolved_typed": all(
                    r.ok or r.error is not None
                    for _, r in over_responses),
                "p99_admitted_ms": esnap["latency_ms"].get("p99"),
                "worker_trajectory": trajectory,
                "scale_up_events": ehealth["elastic"]["scale_up_events"],
                "scale_down_events":
                    ehealth["elastic"]["scale_down_events"],
                "aot": ehealth.get("aot"),
                # every admitted ok answer equals the fixed (single-worker,
                # mesh-free reference) offline result bit-for-bit
                "admitted_match_reference": all(
                    not r.ok or (
                        np.array_equal(r.consensus, offline[i].consensus)
                        and r.score == offline[i].score)
                    for i, r in over_responses),
            }
        finally:
            _aot.deactivate()
            shutil.rmtree(aot_dir, ignore_errors=True)
    print(json.dumps(out))


def _multichip_arg(flag, default):
    if flag in sys.argv:
        return int(sys.argv[sys.argv.index(flag) + 1])
    return default


def _multichip_mode():
    """Read-axis scaling + fleet throughput across the available devices.

    Two measurements, one MULTICHIP JSON line:

    1. ONE north-star-scale consensus (2048 x 1 kb, full batch) with its
       read axis sharded over 1/2/4/8-device meshes
       (parallel.sharding.mesh_fused_step_pallas under the driver) —
       wall time, speedup vs the 1-device run, consensus bit-identity
       against the unsharded oracle, and the utils.roofline
       mesh_fused_model prediction (per-device HBM bytes + the ICI
       collective term) next to each measured point;
    2. the device-parallel FLEET (sweep_clusters_sharded n_workers — one
       pinned executor per device) on a heterogeneous serving workload:
       requests/sec and requests/sec/chip per fleet size.

    Device counts are capped by ``len(jax.devices())`` — run under
    XLA_FLAGS=--xla_force_host_platform_device_count=8 for a virtual
    curve (identity still meaningful; the walls then share one host's
    cores and measure overhead, not scaling). Smoke overrides:
    --multichip-reads N, --multichip-len N, --multichip-timed N,
    --multichip-serve-n N.
    """
    import jax

    from rifraf_tpu.engine.driver import rifraf
    from rifraf_tpu.engine.params import RifrafParams
    from rifraf_tpu.parallel.sharding import make_mesh
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded
    from rifraf_tpu.utils import roofline as _roofline

    n_reads = _multichip_arg("--multichip-reads", 2048)
    tlen = _multichip_arg("--multichip-len", 1000)
    n_timed = _multichip_arg("--multichip-timed", 2)
    serve_n = _multichip_arg("--multichip-serve-n", 256)

    n_dev = len(jax.devices())
    counts = [k for k in (1, 2, 4, 8) if k <= n_dev]
    template, seqs, phreds = build_e2e_problem(tlen, n_reads)

    def one(mesh):
        params = RifrafParams(batch_size=0, batch_fixed=False,
                              do_alignment_proposals=False, mesh=mesh)
        walls = []
        result = None
        for i in range(n_timed + 1):  # first run compiles
            t0 = time.perf_counter()
            result = rifraf(seqs, phreds=phreds, params=params)
            if i > 0:
                walls.append(time.perf_counter() - t0)
        return walls, result

    out = {
        "config": f"multichip_{n_reads}x{tlen}",
        "backend": jax.default_backend(),
        "n_devices_visible": n_dev,
        "n_reads": n_reads,
        "tlen": tlen,
    }

    _roofline.clear()
    scaling = []
    base_wall = None
    oracle = None
    for k in counts:
        mesh = make_mesh(k) if k > 1 else None
        walls, result = one(mesh)
        wall = min(walls)
        if k == 1:
            base_wall, oracle = wall, result
        entry = {
            "devices": k,
            "wall_s": round(wall, 3),
            "runs_s": [round(w, 3) for w in walls],
            "speedup_vs_1dev": round(base_wall / wall, 2),
            "scaling_efficiency": round(base_wall / wall / k, 3),
            "identical_to_1dev": bool(np.array_equal(
                result.consensus, oracle.consensus)),
        }
        recs = [r for r in _roofline.snapshot()
                if r["kernel"] == "mesh_fused_step"
                and r["n_devices"] == k]
        if recs:
            r = recs[-1]
            entry["model"] = {
                "bytes_per_device_gb": round(
                    r["model_bytes_per_device"] / 1e9, 3),
                "ici_bytes_per_device": r["ici_bytes_per_device"],
                "speedup": round(r["model_speedup"], 2),
                "scaling_efficiency": round(r["scaling_efficiency"], 3),
            }
        scaling.append(entry)
    out["read_axis_scaling"] = scaling
    out["identity"] = ("ok" if all(e["identical_to_1dev"]
                                   for e in scaling) else "MISMATCH")

    # fleet: one pinned executor per device on a heterogeneous request
    # stream — throughput must scale with chips because the problems are
    # independent (the embarrassingly parallel regime the read-axis mesh
    # complements)
    rng = np.random.default_rng(21)
    clusters = _serve_workload(serve_n, rng)
    fleet = []
    fleet_oracle = None
    for k in counts:
        sweep_clusters_sharded(clusters, n_workers=k)  # warm compiles
        t0 = time.perf_counter()
        res = sweep_clusters_sharded(clusters, n_workers=k)
        wall = time.perf_counter() - t0
        if k == 1:
            fleet_oracle = res
        rps = serve_n / wall
        fleet.append({
            "workers": k,
            "wall_s": round(wall, 3),
            "rps": round(rps, 2),
            "rps_per_chip": round(rps / k, 2),
            "identical_to_1worker": all(
                np.array_equal(a.consensus, b.consensus)
                and a.score == b.score
                for a, b in zip(res, fleet_oracle)
            ),
        })
    out["fleet"] = {"n_requests": serve_n, "scaling": fleet}
    print("MULTICHIP " + json.dumps(out))


def _lint_stats() -> dict:
    """Bench hygiene: the rifraf-lint analyzer's wall time and finding
    counts ride the headline BENCH JSON so the invariant suite's cost
    (and cleanliness) stays visible as the tree grows. Never fails the
    bench — CI's lint-invariants job owns the hard gate."""
    import os

    from rifraf_tpu.analysis import run_all

    try:
        report = run_all(os.path.dirname(os.path.abspath(__file__)))
    except Exception as e:  # pragma: no cover - diagnostic only
        return {"error": f"{type(e).__name__}: {e}"}
    return {
        "wall_s": round(report["wall_s"], 3),
        "findings": len(report["findings"]),
        "suppressed": report["suppressed"],
        "per_pass": report["per_pass"],
    }


def main():
    if "--cpu" in sys.argv:
        import os

        import jax

        # the env var alone is IGNORED when an accelerator plugin is
        # ambient (measured on the tunneled-TPU host: JAX_PLATFORMS=cpu
        # still initialized the TPU); the config option always wins, set
        # it before anything touches a backend (tests/conftest.py:17-19)
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
        if jax.default_backend() != "cpu":
            raise RuntimeError(
                f"--cpu requested but backend is {jax.default_backend()}"
            )
    if "--step" in sys.argv:
        _step_mode()
        return 0
    if "--northstar" in sys.argv:
        _northstar_mode()
        return 0
    if "--golden" in sys.argv:
        _golden_mode()
        return 0
    if "--sweep" in sys.argv:
        _sweep_mode()
        return 0
    if "--precision" in sys.argv:
        _precision_mode()
        return 0
    if "--serve" in sys.argv:
        _serve_mode()
        return 0
    if "--multichip" in sys.argv:
        _multichip_mode()
        return 0
    if "--refdefault" in sys.argv:
        # standalone ref-default measurement (use with --cpu to
        # recalibrate CPU_REF_DEFAULT_SECONDS)
        import jax

        from rifraf_tpu.utils import roofline as _roofline

        _roofline.clear()
        # device_loop="on": off-TPU the auto gate would fall back to the
        # host loop, where the packed/unpacked comparison measures
        # nothing and no stage runner records lane stats
        walls, it, rec, res = _with_segment_pack("1", lambda: measure_e2e(
            n_timed=2, verbose=True, ref_default=True, device_loop="on"))
        lane = ref_default_lane_stats()
        # the same stage batches without segment-pair packing: the
        # rollback re-score as a conditional second dispatch
        walls_u, _, _, _ = _with_segment_pack("0", lambda: measure_e2e(
            n_timed=2, verbose=True, ref_default=True, device_loop="on"))
        # the same config pinned to the per-iteration host loop: what
        # each iteration pays in device round-trips (the latency the
        # device-resident stage loop amortizes into one dispatch/stage)
        walls_h, _, _, res_h = measure_e2e(n_timed=2, verbose=True,
                                           ref_default=True,
                                           device_loop="off")
        print(json.dumps({
            "config": "ref_default_1kb_256",
            "backend": jax.default_backend(),
            "e2e_seconds": round(min(walls), 3),
            "runs_s": [round(w, 3) for w in walls],
            "iterations": it,
            "template_recovered": rec,
            "stage_paths": res.metadata["stage_paths"],
            "lane_stats": lane,
            "stage_batch": {
                "packed_s": round(min(walls), 3),
                "unpacked_s": round(min(walls_u), 3),
                "packed_vs_unpacked": round(min(walls_u) / min(walls), 2),
            },
            "host_loop": dict(host_dispatch_stats(res_h, walls_h),
                              e2e_seconds=round(min(walls_h), 3)),
            "speculation": speculation_block(
                n_timed=1, ref_default=True, device_loop="on"),
        }))
        return 0

    import jax

    verbose = "--verbose" in sys.argv
    if "--cpu" in sys.argv and "--quick" not in sys.argv:
        # the CPU backend re-measures the headline only (the north-star
        # config costs ~6 min per run there; its constant comes from
        # BASELINE.md's recorded measurement)
        sys.argv.append("--quick")
    walls, n_iters, recovered, _ = measure_e2e(verbose=verbose)
    wall = min(walls)
    out = {
        "metric": "rifraf_e2e_1kb_256reads_seconds",
        "value": round(wall, 3),
        "unit": "s",
        "vs_baseline": round(CPU_E2E_SECONDS / wall, 2),
        "runs_s": [round(w, 3) for w in walls],
        "baseline_measured": CPU_BASELINE_META,
        "iterations": n_iters,
        "template_recovered": recovered,
        "backend": jax.default_backend(),
    }
    if "--quick" not in sys.argv:
        # driver-capture the north-star config (the >=50x target is
        # DEFINED on 2048 x 1 kb — BASELINE.json) in the same JSON line
        from rifraf_tpu.utils import roofline as _roofline

        _roofline.clear()
        walls_ns, it_ns, rec_ns, res_ns = measure_e2e(
            tlen=1000, n_reads=2048, n_timed=2, verbose=verbose
        )
        ns = min(walls_ns)
        out["northstar_2048x1kb"] = {
            "value": round(ns, 3),
            "runs_s": [round(w, 3) for w in walls_ns],
            "vs_baseline": round(CPU_NORTHSTAR_SECONDS / ns, 2),
            "cpu_baseline_s": CPU_NORTHSTAR_SECONDS,
            "iterations": it_ns,
            "template_recovered": rec_ns,
            "roofline": roofline_stats(res_ns),
            "speculation": speculation_block(tlen=1000, n_reads=2048,
                                             n_timed=1),
        }
        # do_score=True at the north-star shape: the quality-estimation
        # tail (SCORE-stage realign with the on-core stats kernel + move
        # fetch, dense-table quality readout, pileup probabilities) on
        # top of the consensus loop — the sections the round-6 stats
        # kernel and the vectorized estimate_probs readout target
        _roofline.clear()
        walls_ds, it_ds, rec_ds, res_ds = measure_e2e(
            tlen=1000, n_reads=2048, n_timed=1, verbose=verbose,
            do_score=True,
        )
        td = res_ds.timers.to_dict()
        out["do_score_2048x1kb"] = {
            "value": round(min(walls_ds), 3),
            "runs_s": [round(w, 3) for w in walls_ds],
            "iterations": it_ds,
            "template_recovered": rec_ds,
            "score_sections_s": {
                k: td[k]["seconds"]
                for k in ("realign_rescore", "estimate_probs",
                          "moves_fetch", "tables_readout")
                if k in td
            },
            "roofline": roofline_stats(res_ds),
        }
        # and the REFERENCE-DEFAULT parameter set (what cli/consensus.py
        # runs): fixed top-5 INIT batch, batch growth, alignment proposals
        _roofline.clear()
        # device_loop="on": the stage-batch comparison needs the stage
        # runner engaged (auto declines off-TPU, where the host loop
        # would make packed vs unpacked a no-op measurement)
        walls_rd, it_rd, rec_rd, res_rd = _with_segment_pack(
            "1", lambda: measure_e2e(
                n_timed=2, verbose=verbose, ref_default=True,
                device_loop="on",
            )
        )
        lane_rd = ref_default_lane_stats()
        # the same stage batches with segment-pair packing off: the
        # packed-vs-unpacked comparison rides the JSON alongside the
        # lane stats
        walls_ru, _, _, _ = _with_segment_pack(
            "0", lambda: measure_e2e(
                n_timed=2, verbose=verbose, ref_default=True,
                device_loop="on",
            )
        )
        # per-iteration host-dispatch latency of the SAME config with
        # the device loop off: the round-trip cost the device-resident
        # stage loop removes
        walls_rh, _, _, res_rh = measure_e2e(
            n_timed=2, verbose=verbose, ref_default=True,
            device_loop="off"
        )
        rd = min(walls_rd)
        out["ref_default_1kb_256"] = {
            "value": round(rd, 3),
            "runs_s": [round(w, 3) for w in walls_rd],
            "iterations": it_rd,
            "template_recovered": rec_rd,
            "stage_paths": res_rd.metadata["stage_paths"],
            "lane_stats": lane_rd,
            "stage_batch": {
                "packed_s": round(rd, 3),
                "unpacked_s": round(min(walls_ru), 3),
                "packed_vs_unpacked": round(min(walls_ru) / rd, 2),
            },
            "host_loop": dict(host_dispatch_stats(res_rh, walls_rh),
                              e2e_seconds=round(min(walls_rh), 3)),
        }
        if CPU_REF_DEFAULT_SECONDS:
            out["ref_default_1kb_256"]["vs_baseline"] = round(
                CPU_REF_DEFAULT_SECONDS / rd, 2
            )
    out["lint"] = _lint_stats()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
