"""Headline benchmark: the fused consensus step on 1 kb x 256 reads.

One step = batched banded forward + backward fills plus dense rescoring of
ALL 9xLen+4 single-base edits against every read — the per-iteration
device work of the reference's hill-climbing loop (align.jl:155-212 fills
+ model.jl:242-285/401-456 rescoring, BASELINE.json config "1 kb template
x 256 reads"), issued as ONE fused XLA dispatch with device-resident
inputs (rifraf_tpu.ops.fused).

Timing is honest against runtime-side result reuse: every timed iteration
uses a slightly perturbed score table (distinct content), and each call is
individually blocked.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

`vs_baseline` is the speedup over this repo's measured CPU-backend number:
the SAME fused-step program on jax-CPU on this host class (multithreaded
XLA:CPU — a far stronger host baseline than the r1 scan-per-column CPU
number; see BASELINE.md "measured baselines").
"""

import json
import sys
import time

import numpy as np

# CPU-backend measurement of the identical fused step on the dev host
# (python bench.py --cpu; recorded in BASELINE.md): 1.294 s/step.
CPU_BASELINE_STEP_SECONDS = 1.294

TLEN = 1000
N_READS = 256
BANDWIDTH = 16
N_TIMED = 5


def build_problem():
    from rifraf_tpu.models.errormodel import ErrorModel, Scores
    from rifraf_tpu.models.sequences import batch_reads, make_read_scores

    scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
    rng = np.random.default_rng(0)
    template = rng.integers(0, 4, size=TLEN).astype(np.int8)
    reads = []
    for _ in range(N_READS):
        slen = int(rng.integers(950, 1050))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, BANDWIDTH, scores))
    return template, batch_reads(reads, dtype=np.float32)


def measure():
    import jax
    import jax.numpy as jnp

    from rifraf_tpu.ops import align_jax
    from rifraf_tpu.ops.fused import fused_step

    template, batch = build_problem()
    tlen = TLEN
    K = align_jax.band_height(batch, tlen)
    geom = align_jax.batch_geometry(batch, tlen)
    t_dev = jnp.asarray(np.pad(template, (0, 24)), jnp.int8)
    w = jnp.ones(N_READS, jnp.float32)

    base_match = np.asarray(batch.match)
    seq_d = jnp.asarray(batch.seq)
    mm_d = jnp.asarray(batch.mismatch)
    ins_d = jnp.asarray(batch.ins)
    dels_d = jnp.asarray(batch.dels)

    def run(i):
        # distinct content per timed call defeats any result reuse
        m = jnp.asarray(base_match * (1.0 + 1e-6 * i))
        jax.block_until_ready(m)
        t0 = time.perf_counter()
        r = fused_step(t_dev, seq_d, m, mm_d, ins_d, dels_d, geom, w, K)
        jax.block_until_ready(r)
        return time.perf_counter() - t0

    run(0)  # compile
    times = [run(i + 1) for i in range(N_TIMED)]
    return min(times)


def main():
    if "--cpu" in sys.argv:
        import os

        # force-assign: an ambient JAX_PLATFORMS (e.g. a TPU plugin) would
        # silently put the "CPU baseline" on the accelerator
        os.environ["JAX_PLATFORMS"] = "cpu"
    dt = measure()
    # every substitution (4xT, incl. identity), insertion (4x(T+1)),
    # and deletion (T) is scored against every read in the step
    P = 4 * TLEN + 4 * (TLEN + 1) + TLEN
    value = N_READS * P / dt
    baseline_value = N_READS * P / CPU_BASELINE_STEP_SECONDS
    out = {
        "metric": "proposal_scores_per_sec_1kb_256reads_fused",
        "value": round(value, 1),
        "unit": "proposal-scores/s",
        "vs_baseline": round(value / baseline_value, 2),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
