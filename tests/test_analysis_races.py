"""Runtime half of the rifraf-lint ``races`` pass: the LockTracker
harness from ``rifraf_tpu.analysis.locktrack`` instruments LIVE
instances of the serve shared-state classes and a barrier-synchronized
multi-thread stress asserts ZERO recorded violations.

The detector is deterministic where timing-based race tests are flaky:
every unguarded mutation is recorded on every schedule, not only on the
schedules where two threads actually collide — the negative-control
tests below prove a single unguarded write from a single thread is
caught. This file runs inside the CI chaos job under both
``RIFRAF_TPU_FUSED_IMPL`` legs; nothing here touches a kernel, so the
legs only vary the imported module graph.
"""

import io
import threading
import time
import types
from concurrent.futures import Future

import pytest

from rifraf_tpu.analysis.locktrack import (
    LockTracker,
    TrackedCondition,
    TrackedLock,
    track_instance,
)
from rifraf_tpu.serve.request import Request, ServeConfig

N_THREADS = 6
N_OPS = 200


def hammer(n_threads, fn):
    """Run ``fn(worker_index)`` on n_threads barrier-synchronized
    threads; re-raise the first worker exception."""
    barrier = threading.Barrier(n_threads)
    errors = []

    def runner(i):
        try:
            barrier.wait(timeout=30)
            fn(i)
        except BaseException as e:  # noqa: BLE001 - reported below
            errors.append(e)

    threads = [threading.Thread(target=runner, args=(i,),
                                name=f"hammer-{i}")
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads), "stress deadlocked"
    if errors:
        raise errors[0]


def make_request(rid, key=(8, 64, 16, 4)):
    return Request(id=str(rid), cluster=[], info=None, key=key,
                   t_submit=time.perf_counter(), deadline=None)


# ---------------------------------------------------------------------
# tracked-primitive sanity
# ---------------------------------------------------------------------

def test_tracked_lock_ownership():
    lk = TrackedLock()
    assert not lk.held_by_me()
    with lk:
        assert lk.held_by_me()
    assert not lk.held_by_me()


def test_tracked_condition_clears_owner_during_wait():
    cv = TrackedCondition()
    seen = []

    def waiter():
        with cv:
            cv.wait_for(lambda: seen, timeout=10)
            seen.append("woke-holding" if cv.held_by_me() else "woke-bare")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    # the waiter is parked in wait_for, so ITS ownership must be
    # released — this thread can take the condition
    with cv:
        assert cv.held_by_me()
        seen.append("signal")
        cv.notify_all()
    t.join(timeout=10)
    assert seen == ["signal", "woke-holding"]


# ---------------------------------------------------------------------
# stress: zero violations on the real classes
# ---------------------------------------------------------------------

def test_server_stats_stress():
    from rifraf_tpu.serve.stats import ServerStats

    tracker = LockTracker()
    stats = track_instance(ServerStats(), tracker)

    def work(i):
        for k in range(N_OPS):
            stats.count("ops")
            stats.note_service(0.001 * (k + 1))
            stats.note_queue_wait(0.0005)
            stats.observe_latency(0.002)
            stats.note_batch(2, 4, useful_cells=10, padded_cells=6,
                             useful_lanes=16, lane_slots=1,
                             cluster_lanes=24)
            stats.note_declines([{"stage": "sweep", "reason": "band"}])
            stats.get("ops")
            if k % 50 == 0:
                stats.snapshot(queue_depth=k)

    hammer(N_THREADS, work)
    assert [str(v) for v in tracker.violations] == []
    # lock discipline also means no lost increments
    assert stats.get("ops") == N_THREADS * N_OPS


def test_device_scoreboard_stress():
    from rifraf_tpu.serve.quarantine import DeviceScoreboard

    tracker = LockTracker()
    board = track_instance(DeviceScoreboard(threshold=50), tracker)

    def work(i):
        dev = f"dev-{i % 2}"
        for k in range(N_OPS):
            board.record_trip(dev, "guard" if k % 2 else "divergence")
            board.is_quarantined(dev)
            if k % 25 == 0:
                board.note_probe(dev, ok=True)

    hammer(N_THREADS, work)
    assert [str(v) for v in tracker.violations] == []


def test_micro_batcher_stress():
    from rifraf_tpu.serve.batcher import MicroBatcher

    tracker = LockTracker()
    config = ServeConfig(max_batch=4, segment_pack=False)
    batcher = track_instance(MicroBatcher(config), tracker)
    flushed = []
    flushed_mu = threading.Lock()

    def work(i):
        for k in range(N_OPS):
            flush = batcher.add(make_request(f"{i}-{k}"))
            if flush:
                with flushed_mu:
                    flushed.extend(flush)
            batcher.depth()
            now = time.perf_counter()
            due = batcher.due(now)
            if due:
                with flushed_mu:
                    for b in due:
                        flushed.extend(b)
            batcher.next_due(now)

    hammer(N_THREADS, work)
    for bucket in batcher.drain():
        flushed.extend(bucket)
    assert [str(v) for v in tracker.violations] == []
    # conservation: every admitted request is in exactly one flush
    assert len(flushed) == N_THREADS * N_OPS
    assert len({r.id for r in flushed}) == N_THREADS * N_OPS


def test_timers_exact_counts_under_contention():
    from rifraf_tpu.utils.timers import Timers

    tracker = LockTracker()
    timers = track_instance(Timers(), tracker)
    other = Timers()
    other.add("merged", 0.5)

    def work(i):
        for _k in range(N_OPS):
            timers.add("hot", 0.001)
        timers.merge(other)
        timers.summary()
        timers.to_dict()

    hammer(N_THREADS, work)
    assert [str(v) for v in tracker.violations] == []
    # the regression the Timers lock fixed: an unsynchronized dict RMW
    # loses increments under contention; the count must be EXACT
    assert timers.to_dict()["hot"]["calls"] == N_THREADS * N_OPS
    assert timers.to_dict()["merged"]["calls"] == N_THREADS


def test_emitter_stress():
    from rifraf_tpu.cli.serve import _Emitter

    tracker = LockTracker()
    emitter = track_instance(_Emitter(io.StringIO()), tracker)

    def work(i):
        for k in range(N_OPS // 4):
            emitter.expect()
            fut = Future()
            fut.set_result(types.SimpleNamespace(
                to_json_dict=lambda i=i, k=k: {"id": f"{i}-{k}",
                                               "ok": True}))
            emitter.emit_response(fut)

    hammer(N_THREADS, work)
    assert emitter.drain(timeout_s=10)
    assert [str(v) for v in tracker.violations] == []
    lines = emitter.fh.getvalue().splitlines()
    assert len(lines) == N_THREADS * (N_OPS // 4)


def test_worker_inflight_handoff_ownership():
    """The Worker is deliberately lock-free: its supervision surface
    (last_beat/busy/inflight/draining/drained) is single-writer
    GIL-atomic rebinds, recovered by the supervisor only after the
    worker thread is dead. The tracker journals every write so the test
    can assert that ownership story instead of just 'no crash'."""
    from rifraf_tpu.serve.worker import Worker

    tracker = LockTracker()
    w = Worker.__new__(Worker)
    # only the supervision surface; skipping __init__ avoids building a
    # ChunkExecutor (jax) for what is a pure threading test
    w.last_beat = time.perf_counter()
    w.busy = False
    w.inflight = []
    w.draining = False
    w.drained = False
    w._last_probe = -float("inf")
    track_instance(w, tracker)
    stop = threading.Event()
    recovered = []

    def worker_thread():
        for _k in range(N_OPS):
            w.busy = True
            w.inflight = [object(), object()]
            w._heartbeat()
            w.inflight = []
            w.busy = False
        w.draining = True
        w.drained = True
        stop.set()

    def supervisor_thread():
        while not stop.is_set():
            _ = w.last_beat
            time.sleep(0.0005)
        recovered.extend(w.take_inflight())

    tw = threading.Thread(target=worker_thread, name="worker-0")
    ts = threading.Thread(target=supervisor_thread, name="supervisor")
    tw.start()
    ts.start()
    tw.join(timeout=60)
    ts.join(timeout=60)
    assert [str(v) for v in tracker.violations] == []
    # every supervision write is journaled; the run loop's attrs are
    # written by the worker thread, the recovery swap by the supervisor
    writes = tracker.writes
    assert set(writes[("Worker", "busy")]) == {"worker-0"}
    assert writes[("Worker", "inflight")].count("supervisor") == 1
    assert set(writes[("Worker", "inflight")]) == {"worker-0",
                                                   "supervisor"}
    assert recovered == []  # worker left a clean (empty) slot


# ---------------------------------------------------------------------
# negative controls: the detector actually detects
# ---------------------------------------------------------------------

def test_detects_unguarded_container_mutation():
    from rifraf_tpu.serve.batcher import MicroBatcher

    tracker = LockTracker()
    batcher = track_instance(
        MicroBatcher(ServeConfig(segment_pack=False)), tracker)
    # bypass the API: item-write the shared dict without the lock —
    # exactly what the pre-fix depth()/add() interleaving amounted to
    batcher._pending[("blk", 1, 2, 3, 4)] = [make_request("rogue")]
    assert len(tracker.violations) == 1
    v = tracker.violations[0]
    assert (v.cls, v.attr) == ("MicroBatcher", "_pending")
    assert "__setitem__" in v.op
    # ... while the same write under the lock is clean
    with batcher._lock:
        batcher._pending.pop(("blk", 1, 2, 3, 4))
    assert len(tracker.violations) == 1


def test_detects_unguarded_rebind():
    from rifraf_tpu.serve.stats import ServerStats
    from rifraf_tpu.serve.worker import Worker

    tracker = LockTracker()
    stats = track_instance(ServerStats(), tracker)
    stats._batches = 99  # rebind without holding stats._lock
    assert [v.attr for v in tracker.violations] == ["_batches"]

    tracker2 = LockTracker()
    w = Worker.__new__(Worker)
    w.inflight = []
    track_instance(w, tracker2)
    w.dev_key = "rogue"  # not on the Worker allowlist, no lock to hold
    assert [(v.cls, v.attr) for v in tracker2.violations] == \
        [("Worker", "dev_key")]
    assert "unguarded" in str(tracker2.violations[0])


def test_track_instance_rejects_unregistered_class():
    tracker = LockTracker()
    with pytest.raises(KeyError):
        track_instance(object(), tracker)
