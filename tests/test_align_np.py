"""Alignment-engine oracle tests, ported from /root/reference/test/test_align.jl.

These exercise the numpy reference engine (rifraf_tpu.ops.align_np); the JAX
kernels are tested for equivalence against this engine in test_align_jax.py.
"""

import numpy as np

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.ops import align_np as al
from rifraf_tpu.utils import encode_seq, decode_seq


def inv_log10(lp):
    return np.log10(1.0 - 10.0**lp)


def colmax(A, B, j):
    """max_i(A[i,j] + B[i,j]) over the in-band overlap of column j."""
    a_start, a_stop = A.row_range(j)
    b_start, b_stop = B.row_range(j)
    start = max(a_start, b_start)
    stop = min(a_stop, b_stop)
    if stop < start:
        return -np.inf
    acol = np.array([A[i, j] for i in range(start, stop + 1)])
    bcol = np.array([B[i, j] for i in range(start, stop + 1)])
    return np.max(acol + bcol)


def check_all_cols(A, B, codon_moves: bool):
    """The forward/backward consistency invariant (test_utils.jl:6-23):
    for every column j, max_i(A[i,j] + B[i,j]) == A[end,end]. With codon
    moves, every 3-column window must contain the correct score."""
    expected = A[A.nrows - 1, A.ncols - 1]
    assert np.isclose(expected, B[0, 0], atol=1e-6)
    ncols = A.ncols
    if codon_moves:
        for j in range(ncols - 2):
            best = max(colmax(A, B, jj) for jj in (j, j + 1, j + 2))
            assert np.isclose(best, expected, atol=1e-6), f"cols {j}..{j+2}: {best} != {expected}"
    else:
        for j in range(ncols):
            best = colmax(A, B, j)
            assert np.isclose(best, expected, atol=1e-6), f"col {j}: {best} != {expected}"


SCORES = Scores(-1.0, -1.0, -1.0, -np.inf, -np.inf)


def make_pseq(seq, log_p, bandwidth, scores=SCORES):
    return make_read_scores(seq, np.asarray(log_p, dtype=np.float64), bandwidth, scores)


def test_perfect_forward():
    lp = -3.0
    match = inv_log10(lp)
    pseq = make_pseq("AA", [lp, lp], 1)
    A = al.forward(encode_seq("AA"), pseq)
    expected = np.array(
        [
            [0.0, lp + SCORES.deletion, 0.0],
            [lp + SCORES.insertion, match, match + lp + SCORES.deletion],
            [0.0, match + lp + SCORES.insertion, 2 * match],
        ]
    )
    np.testing.assert_allclose(A.full(), expected, atol=1e-9)

    A2, _ = al.forward_moves(encode_seq("AA"), pseq)
    np.testing.assert_allclose(A2.full(), A.full(), atol=1e-9)


def test_perfect_backward():
    lp = -3.0
    match = inv_log10(lp)
    pseq = make_pseq("AA", [lp, lp], 1)
    B = al.backward(encode_seq("AA"), pseq)
    expected = np.array(
        [
            [2 * match, match + lp + SCORES.insertion, 0.0],
            [match + lp + SCORES.deletion, match, lp + SCORES.insertion],
            [0.0, lp + SCORES.deletion, 0.0],
        ]
    )
    np.testing.assert_allclose(B.full(), expected, atol=1e-9)


def test_imperfect_forward():
    lp = -3.0
    match = inv_log10(lp)
    pseq = make_pseq("AT", [lp, lp], 1)
    A1 = al.forward(encode_seq("AA"), pseq)
    B = al.backward(encode_seq("AA"), pseq)
    check_all_cols(A1, B, False)
    expected = np.array(
        [
            [0.0, lp + SCORES.deletion, 0.0],
            [lp + SCORES.insertion, match, match + lp + SCORES.deletion],
            [0.0, match + lp + SCORES.insertion, match + lp + SCORES.mismatch],
        ]
    )
    np.testing.assert_allclose(A1.full(), expected, atol=0.01)
    A2, _ = al.forward_moves(encode_seq("AA"), pseq)
    np.testing.assert_allclose(A1.full(), A2.full(), atol=0.01)


def test_imperfect_backward():
    lp = -3.0
    match = inv_log10(lp)
    pseq = make_pseq("AT", [lp, lp], 1)
    B = al.backward(encode_seq("AA"), pseq)
    expected = np.array(
        [
            [lp + SCORES.mismatch + match, lp + SCORES.insertion + match, 0.0],
            [2 * lp + SCORES.deletion + SCORES.mismatch, lp + SCORES.mismatch, lp + SCORES.insertion],
            [0.0, lp + SCORES.deletion, 0.0],
        ]
    )
    np.testing.assert_allclose(B.full(), expected, atol=0.01)


def test_forward_backward_agreement_1():
    # codon-enabled scores
    local_scores = Scores.from_error_model(ErrorModel(2.0, 1.0, 1.0, 3.0, 3.0))
    pseq = make_pseq("GTCG", [-1.2, -0.8, -0.7, -1.0], 5, local_scores)
    t = encode_seq("TG")
    A = al.forward(t, pseq)
    B = al.backward(t, pseq)
    check_all_cols(A, B, True)
    A2, _ = al.forward_moves(t, pseq)
    np.testing.assert_allclose(A.full(), A2.full(), atol=0.01)


def test_forward_backward_agreement_2():
    local_scores = Scores.from_error_model(ErrorModel(2.0, 1.0, 1.0, 3.0, 3.0))
    pseq = make_pseq("GACAC", [-1.1, -1.1, -0.4, -1.0, -0.7], 5, local_scores)
    t = encode_seq("GCACGGTC")
    A = al.forward(t, pseq)
    B = al.backward(t, pseq)
    check_all_cols(A, B, True)


def test_insertion_agreement():
    log_p = [-5.0, -1.0, -6.0]
    pseq = make_pseq("ATA", log_p, 10)
    t = encode_seq("AA")
    A = al.forward(t, pseq)
    B = al.backward(t, pseq)
    score = inv_log10(log_p[0]) + log_p[1] + SCORES.insertion + inv_log10(log_p[2])
    assert np.isclose(A[A.nrows - 1, A.ncols - 1], score)
    check_all_cols(A, B, False)


def test_deletion_agreement_1():
    log_p = [-5.0, -2.0, -1.0, -6.0]
    pseq = make_pseq("GAAG", log_p, 10)
    t = encode_seq("GATAG")
    A = al.forward(t, pseq)
    B = al.backward(t, pseq)
    score = (
        pseq.match_scores[0]
        + pseq.match_scores[1]
        + pseq.del_scores[2]
        + pseq.match_scores[2]
        + pseq.match_scores[3]
    )
    assert np.isclose(A[A.nrows - 1, A.ncols - 1], score)
    check_all_cols(A, B, False)


def test_deletion_agreement_2():
    log_p = [-2.0, -3.0]
    pseq = make_pseq("AA", log_p, 10)
    t = encode_seq("ATA")
    A = al.forward(t, pseq)
    B = al.backward(t, pseq)
    score = pseq.match_scores[0] + pseq.del_scores[1] + pseq.match_scores[1]
    assert np.isclose(A[A.nrows - 1, A.ncols - 1], score)
    check_all_cols(A, B, False)


ALIGN_SCORES = Scores.from_error_model(ErrorModel(1.0, 1.0, 1.0, 0.0, 0.0))


def aligned_to_str(arr):
    return "".join("-" if c < 0 else "ACGT"[c] for c in arr)


def test_align_1():
    pseq = make_pseq("AAA", [-2.0, -3.0, -3.0], 10, ALIGN_SCORES)
    moves = al.align_moves(encode_seq("ATAA"), pseq)
    t, s = al.moves_to_aligned_seqs(moves, encode_seq("ATAA"), pseq.seq)
    assert aligned_to_str(t) == "ATAA"
    assert aligned_to_str(s) == "A-AA"


def test_align_2():
    pseq = make_pseq("AAACCCTT", [np.log10(0.1)] * 8, 10, ALIGN_SCORES)
    moves = al.align_moves(encode_seq("AACCTT"), pseq)
    t, s = al.moves_to_aligned_seqs(moves, encode_seq("AACCTT"), pseq.seq)
    assert aligned_to_str(t)[-2:] == "TT"


def test_moves_to_indices():
    cases = [
        ("AAA", "AAA", [1, 2, 3]),
        ("AAA", "AAAT", [1, 2, 3]),
        ("AAAT", "AAA", [1, 2, 3, 3]),
        ("TAAA", "AAA", [0, 1, 2, 3]),
    ]
    for tstr, sstr, expected in cases:
        pseq = make_pseq(sstr, [np.log10(0.1)] * len(sstr), 10, ALIGN_SCORES)
        moves = al.align_moves(encode_seq(tstr), pseq)
        indices = al.moves_to_indices(moves, len(tstr), len(sstr))
        np.testing.assert_array_equal(indices, expected), (tstr, sstr)


def test_align_and_skew():
    ref_scores = Scores.from_error_model(ErrorModel(10.0, 1e-10, 1e-10, 1.0, 1.0))
    consensus_errors = [-8.0, -8.0, -8.0, -1.0, -8.0, -10.0, -10.0]
    consensus = make_pseq("CTGCCGA", consensus_errors, 10, ref_scores)
    a, b = al.align(encode_seq("CGGCGATTT"), consensus, skew_matches=True)
    assert aligned_to_str(a) == "CGG-CGATTT"
    assert aligned_to_str(b) == "CTGCCGA---"


def test_align_with_self():
    seqstr = "AAAGGGTTTCCC"
    errors = np.full(len(seqstr), 0.1)
    errors[:6] = 0.3
    errors[-4:] = 0.45
    scores = Scores.from_error_model(ErrorModel(1.0, 10.0, 10.0, 0.0, 0.0))
    rseq = make_pseq(seqstr, np.log10(errors), 3, scores)
    a, b = al.align(encode_seq(seqstr), rseq)
    np.testing.assert_array_equal(a, b)
    assert aligned_to_str(a) == seqstr


def test_edit_distance():
    assert al.edit_distance(encode_seq("ACGT"), encode_seq("ACGT")) == 0
    assert al.edit_distance(encode_seq("ACGT"), encode_seq("AGT")) == 1
    assert al.edit_distance(encode_seq("ACGT"), encode_seq("ACCGT")) == 1
    assert al.edit_distance(encode_seq("ACGT"), encode_seq("AAGT")) == 1
