"""Pallas forward kernel vs the XLA scan path (interpret mode on CPU)."""

import numpy as np

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax
from rifraf_tpu.ops.align_pallas import forward_batch_pallas

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0))


def test_pallas_forward_matches_xla():
    rng = np.random.default_rng(0)
    tlen = 33
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for slen in (30, 33, 37, 25):
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, 6, SCORES))
    batch = batch_reads(reads, dtype=np.float32)

    bandsP, scoresP, geomP = forward_batch_pallas(template, batch, interpret=True)
    K = bandsP.shape[1]
    bandsX, _, scoresX, _ = align_jax.forward_batch(template, batch, K=K)

    np.testing.assert_allclose(
        np.asarray(scoresP), np.asarray(scoresX), rtol=1e-4, atol=1e-4
    )
    bp = np.asarray(bandsP)
    bx = np.asarray(bandsX)
    finite = np.isfinite(bx) & (bp > -1e30)
    np.testing.assert_allclose(bp[finite], bx[finite], rtol=1e-4, atol=1e-4)
    # out-of-band cells are "minus infinity" in both representations
    assert (bp[~np.isfinite(bx)] < -1e30).all()


def test_pallas_backward_matches_xla():
    rng = np.random.default_rng(1)
    tlen = 29
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for slen in (26, 29, 34, 22):
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, 6, SCORES))
    batch = batch_reads(reads, dtype=np.float32)

    from rifraf_tpu.ops.align_pallas import backward_batch_pallas

    bandsP, scoresP, _ = backward_batch_pallas(template, batch, interpret=True)
    K = bandsP.shape[1]
    bandsX, scoresX, _ = align_jax.backward_batch(template, batch, K=K)

    np.testing.assert_allclose(
        np.asarray(scoresP), np.asarray(scoresX), rtol=1e-4, atol=1e-4
    )
    bp = np.asarray(bandsP)
    bx = np.asarray(bandsX)
    finite = np.isfinite(bx) & (bp > -1e30)
    np.testing.assert_allclose(bp[finite], bx[finite], rtol=1e-4, atol=1e-4)
    assert (bp[~np.isfinite(bx)] < -1e30).all()


def test_backend_pallas_unavailable_off_tpu():
    """backend="pallas" (the second-generation ops.fill_pallas /
    ops.dense_pallas engines) asserts availability: off-TPU an explicit
    request must fail loudly, never silently run XLA. (This suite runs
    on the forced-CPU backend.)"""
    import pytest

    from rifraf_tpu.engine.realign import BatchAligner
    from rifraf_tpu.models.sequences import make_read_scores

    read = make_read_scores(
        np.array([0, 1, 2, 3], np.int8), np.full(4, -2.0), 3, SCORES
    )
    with pytest.raises(ValueError, match="requires a TPU"):
        BatchAligner([read], dtype=np.float32, backend="pallas")
