"""Tests for the shared VMEM block planner and lane-packing accounting
(utils.shapes) — the single budgeter that replaced the private
fill_pallas._pick_cols / dense_pallas.pick_dense_cols copies."""

import numpy as np
import pytest

from rifraf_tpu.utils import roofline
from rifraf_tpu.utils.shapes import (
    LANES,
    pack_lanes,
    plan_cols,
    pow2_bucket,
)

T1PS = [64, 128, 256, 512, 1088, 4096]
KS = [16, 32, 64, 128]
KERNELS = ["fill", "dense", "stats"]


def _legacy_fill_cols(T1p, K, want_moves=False, budget=9 << 20):
    """fill_pallas._pick_cols as shipped before the hoist (verbatim
    formulas) — the planner must reproduce it bit-for-bit."""
    out_blocks = 2 if want_moves else 1
    best = 1
    c = 1
    while c <= min(T1p, 512):
        if T1p % c == 0 and 2 * 128 * 4 * (
            out_blocks * c * K + 5 * (c + K)
        ) <= budget:
            best = c
        c *= 2
    return best


def _legacy_dense_cols(T1p, K, budget=9 << 20):
    """dense_pallas.pick_dense_cols as shipped before the hoist."""
    best = 1
    c = 1
    while c <= min(T1p // 2, 256):
        if T1p % c == 0 and 2 * 128 * 4 * (
            c * K + (c + 1) * K + 5 * (c + K) + c * 16
        ) <= budget:
            best = c
        c *= 2
    return best


@pytest.mark.parametrize("T1p", T1PS)
@pytest.mark.parametrize("K", KS)
@pytest.mark.parametrize("want_moves", [False, True])
def test_planner_reproduces_legacy_fill(T1p, K, want_moves):
    plan = plan_cols(T1p, K, kernel="fill", want_moves=want_moves)
    assert plan.cols == _legacy_fill_cols(T1p, K, want_moves)


@pytest.mark.parametrize("T1p", T1PS)
@pytest.mark.parametrize("K", KS)
def test_planner_reproduces_legacy_dense(T1p, K):
    plan = plan_cols(T1p, K, kernel="dense")
    assert plan.cols == _legacy_dense_cols(T1p, K)


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("T1p", T1PS)
@pytest.mark.parametrize("K", KS)
def test_budget_monotonicity(kernel, T1p, K):
    """A larger VMEM budget never yields fewer columns."""
    budgets = [1 << 18, 1 << 20, 9 << 20, 1 << 25, 1 << 28]
    cols = [
        plan_cols(T1p, K, kernel=kernel, vmem_budget=b).cols
        for b in budgets
    ]
    assert cols == sorted(cols)


@pytest.mark.parametrize("kernel", KERNELS)
@pytest.mark.parametrize("T1p", T1PS)
@pytest.mark.parametrize("K", KS)
def test_hard_vmem_bound(kernel, T1p, K):
    """Whenever ANY block width fits the budget, the chosen one does
    (best=1 is the forced floor when nothing fits)."""
    # working set at the c=1 floor (budget 0 forces best=1)
    min_need = plan_cols(T1p, K, kernel=kernel, vmem_budget=0).vmem_bytes
    cap_cols = plan_cols(T1p, K, kernel=kernel, vmem_budget=1 << 62).cols
    for budget in (1 << 18, 1 << 20, 9 << 20, 1 << 25):
        plan = plan_cols(T1p, K, kernel=kernel, vmem_budget=budget)
        if min_need <= budget:
            assert plan.vmem_bytes <= budget
        assert plan.cols <= cap_cols
        assert T1p % plan.cols == 0
        assert plan.n_steps * plan.cols == T1p


def test_plan_fields_consistent():
    plan = plan_cols(1088, 32, kernel="dense")
    assert plan.kernel == "dense"
    assert plan.T1p == 1088 and plan.K == 32
    assert plan.vmem_budget == 9 << 20
    assert plan.cols >= 1 and plan.vmem_bytes > 0


def test_pack_lanes_accounting():
    rng = np.random.default_rng(0)
    lens = rng.integers(50, 3000, size=300).tolist()
    pk = pack_lanes(lens)
    # a permutation, with a correct inverse
    assert sorted(pk.order) == list(range(300))
    for i, slot in enumerate(pk.inverse):
        assert pk.order[slot] == i
    assert pk.n_tiles == (300 + LANES - 1) // LANES
    # packed tiles are length-descending, so tile maxima never increase
    assert pk.tile_max == sorted(pk.tile_max, reverse=True)
    assert pk.tile_max[0] == max(lens)
    # packing can only help: packed occupancy >= uniform, both in (0, 1]
    assert 0.0 < pk.uniform_occupancy <= pk.occupancy <= 1.0


def test_pack_lanes_uniform_lengths_full():
    pk = pack_lanes([100] * 256)
    assert pk.occupancy == 1.0 and pk.uniform_occupancy == 1.0
    assert pack_lanes([]).n_tiles == 0


def test_pow2_bucket():
    assert [pow2_bucket(n) for n in (0, 1, 2, 3, 5, 8, 9)] == [
        1, 1, 2, 4, 8, 8, 16,
    ]


def test_roofline_models_positive_and_additive():
    """The fused model is the sum of its parts, and stats rides on top
    only when requested."""
    T1p, K, Npad, C = 1088, 32, 2048, 32
    f = roofline.fill_model(T1p, K, Npad, C, n_streams=2,
                            want_moves=True, moves_lanes=2 * Npad)
    d = roofline.dense_model(T1p, K, Npad, C)
    s = roofline.stats_model(T1p, K, Npad, C)
    base = roofline.fused_model(T1p, K, Npad, C)
    full = roofline.fused_model(T1p, K, Npad, C, want_stats=True)
    assert full["bytes"] == pytest.approx(
        f["bytes"] + d["bytes"] + s["bytes"]
    )
    assert full["bytes"] > base["bytes"] > 0
    assert full["ops"] > base["ops"] > 0
    # int8 panel moves shrink the stats read 4x
    s8 = roofline.stats_model(T1p, K, Npad, C, moves_itemsize=1)
    assert s8["moves_bytes"] * 4 == pytest.approx(s["moves_bytes"])


def test_roofline_utilization_and_registry():
    u = roofline.utilization(roofline.HBM_GBPS * 1e9, 1.0)
    assert u["pct_hbm"] == pytest.approx(100.0)
    assert roofline.utilization(1e9, 0.0) == {"gbps": 0.0, "pct_hbm": 0.0}
    roofline.clear()
    for i in range(300):
        roofline.record("fused_step", i=i, model_bytes=1.0)
    snap = roofline.snapshot()
    assert len(snap) == 256  # bounded
    assert snap[-1]["i"] == 299 and snap[0]["i"] == 44
    roofline.clear()
    assert roofline.snapshot() == []
