"""Durable pipelines: typed validation at every API boundary, the
crash-tolerant streaming ingestion front door (malformed records ->
quarantine sidecar, never a process death), the write-ahead results
journal, and sweep/serve checkpoint-resume after a kill.

Fast tests cover the validation hierarchy, the FASTQ/JSONL fuzz corpus
(zero crashes, 100% quarantined-with-reason), journal torn-tail
recovery, and the watch-scanner rules. Slow tests run the resume grid:
a sweep crashed (exception and SIGKILL) after chunk k resumes
bit-identically recomputing at most one checkpoint interval, and the
serve CLI spool journal round-trips."""

import gzip
import io
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from rifraf_tpu.engine.validate import (
    MAX_PHRED,
    AlphabetError,
    EmptyClusterInputError,
    EmptyReadError,
    InvalidInputError,
    LengthMismatchError,
    PhredRangeError,
    validate_cluster,
    validate_encoded_cluster,
    validate_phreds,
    validate_seq,
)
from rifraf_tpu.io.journal import (
    Journal,
    JournalError,
    fingerprint,
    open_resumable,
    read_journal,
)
from rifraf_tpu.io.stream import (
    QuarantineWriter,
    cluster_key,
    group_clusters,
    journal_path_for,
    quarantine_path_for,
    stream_fastq,
    stream_jsonl,
)

# ------------------------------------------------------------ validation


def test_validation_codes_and_valueerror_compat():
    cases = [
        (lambda: validate_seq(""), EmptyReadError, "zero_length_read"),
        (lambda: validate_seq("ACGN"), AlphabetError, "bad_alphabet"),
        (lambda: validate_seq(np.zeros(0, np.int8)), EmptyReadError,
         "zero_length_read"),
        (lambda: validate_seq(np.array([0, 4], np.int8)), AlphabetError,
         "bad_alphabet"),
        (lambda: validate_phreds([1, -2], 2), PhredRangeError,
         "phred_range"),
        (lambda: validate_phreds([1, MAX_PHRED + 1], 2), PhredRangeError,
         "phred_range"),
        (lambda: validate_phreds([np.nan], 1), PhredRangeError,
         "phred_range"),
        (lambda: validate_phreds([1], 2), LengthMismatchError,
         "length_mismatch"),
        (lambda: validate_cluster([]), EmptyClusterInputError,
         "empty_cluster"),
        (lambda: validate_cluster(["ACGT"], phreds=[[1], [2]]),
         LengthMismatchError, "length_mismatch"),
    ]
    for fn, exc, code in cases:
        with pytest.raises(exc) as ei:
            fn()
        # the whole hierarchy stays ValueError-compatible (existing
        # callers catching ValueError keep working) and every error
        # carries its stable machine-readable code
        assert isinstance(ei.value, InvalidInputError)
        assert isinstance(ei.value, ValueError)
        assert ei.value.code == code


def test_validation_record_context():
    with pytest.raises(AlphabetError) as ei:
        validate_cluster(["ACGT", "ACXT"], phreds=[[9] * 4, [9] * 4],
                         source="reads.fastq", names=["r1", "r2"])
    assert ei.value.context["index"] == 1
    assert ei.value.context["name"] == "r2"
    assert ei.value.context["source"] == "reads.fastq"
    assert "r2" in str(ei.value) and "reads.fastq" in str(ei.value)


def test_max_phred_boundary_accepted():
    validate_phreds([0, MAX_PHRED], 2)  # inclusive range, no raise


def test_phred_bounds_shared_and_edges():
    """One phred window for the whole codebase: utils.phred and
    engine.validate expose the SAME [MIN_PHRED, MAX_PHRED] = [0, 93]
    bounds (Q0 = FASTQ '!' is legal), and validate_phreds accepts both
    edges while rejecting one past each."""
    from rifraf_tpu.engine import validate as ev
    from rifraf_tpu.utils.phred import MAX_PHRED as PM, MIN_PHRED as Pm

    assert ev.MIN_PHRED is Pm and ev.MAX_PHRED is PM
    assert (Pm, PM) == (0, 93)
    validate_phreds([Pm], 1)  # Q0 accepted
    validate_phreds([PM], 1)  # Q93 accepted
    with pytest.raises(PhredRangeError):
        validate_phreds([Pm - 1], 1)
    with pytest.raises(PhredRangeError):
        validate_phreds([PM + 1], 1)
    # the CAP is a config value and still must be >= 1 (capping at 0
    # would declare every base wrong) even though scores of 0 are valid
    from rifraf_tpu.utils.phred import cap_phreds

    np.testing.assert_array_equal(cap_phreds([0, 50, 94], 93),
                                  [0, 50, 93])
    with pytest.raises(ValueError):
        cap_phreds([10], 0)


def test_rifraf_raises_typed_errors_before_dispatch():
    from rifraf_tpu.engine.driver import rifraf

    with pytest.raises(EmptyClusterInputError):
        rifraf([], phreds=[])
    with pytest.raises(PhredRangeError, match="negative"):
        rifraf(["ACGT"], phreds=[np.array([9, 9, 9, -1])])
    with pytest.raises(AlphabetError):
        rifraf(["ACGN"], phreds=[np.full(4, 9)])
    with pytest.raises(LengthMismatchError):
        rifraf(["ACGT"], phreds=[np.full(3, 9)])
    with pytest.raises(EmptyReadError):
        rifraf(["ACGT", ""], phreds=[np.full(4, 9), np.zeros(0)])
    with pytest.raises(ValueError):  # legacy contract intact
        rifraf(["ACGT"])


def test_encode_cluster_raises_typed_errors():
    from rifraf_tpu.serve import encode_cluster

    with pytest.raises(EmptyClusterInputError):
        encode_cluster([], phreds=[])
    with pytest.raises(AlphabetError):
        encode_cluster(["ACGU"], phreds=[np.full(4, 9)])
    with pytest.raises(PhredRangeError):
        encode_cluster(["ACGT"], phreds=[np.array([9, 9, 9, 99.0])])


def test_sweep_rejects_invalid_cluster_before_planning():
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded

    with pytest.raises(EmptyClusterInputError):
        sweep_clusters_sharded([[]])


def test_serve_admission_raises_invalid_request():
    from rifraf_tpu import serve

    class _FakeRead:
        def __len__(self):
            return 0

    cfg = serve.ServeConfig(batch_max_reads=1, supervise=False)
    with serve.ConsensusServer(cfg) as srv:
        with pytest.raises(serve.InvalidRequestError) as ei:
            srv.submit([_FakeRead()])
        assert ei.value.code == "invalid_input"
        assert isinstance(ei.value, serve.ServeError)
        assert "zero_length_read" in str(ei.value)


# ------------------------------------------------- streaming front door

# self-contained corpus cases: (fastq text, n yielded, quarantine
# reasons). Each case re-syncs the 4-line framing at its end, so any
# sequence of cases composes into one corpus with summed expectations.
_CASES = {
    "good": ("@c1/r1\nACGT\n+\nIIII\n", 1, []),
    "crlf": ("@c1/r2\r\nACGT\r\n+\r\nIIII\r\n", 1, []),
    "bad_base": ("@b\nACGN\n+\nIIII\n", 0, ["bad_alphabet"]),
    "empty_qual": ("@b\nACGT\n+\n\n", 0, ["length_mismatch"]),
    "neg_phred": ("@b\nACGT\n+\nII I\n", 0, ["phred_range"]),
    "no_plus": ("@b\nACGT\nACGT\nIIII\n", 0, ["malformed_record"]),
    "bad_header": ("garbage line\n", 0, ["malformed_record"]),
    "empty_read": ("@b\n\n+\n\n", 0, ["zero_length_read"]),
    "blank": ("\n", 0, []),
    # '@' alone or followed by only whitespace: no name field — the
    # record is otherwise well-formed and gets a synthesized name
    "bare_at": ("@\nACGT\n+\nIIII\n", 1, []),
    "ws_header": ("@ \t \nACGT\n+\nIIII\n", 1, []),
    # a non-ASCII quality byte must quarantine, not silently map to '?'
    "nonascii_qual": ("@b\nACGT\n+\nII\xffI\n", 0, ["phred_range"]),
}


def test_fastq_fuzz_corpus_zero_crashes_all_quarantined_with_reason():
    rng = np.random.default_rng(0)
    names = list(_CASES)
    picks = [names[i] for i in rng.integers(0, len(names), 200)]
    corpus = "".join(_CASES[p][0] for p in picks)
    want_yield = sum(_CASES[p][1] for p in picks)
    want_reasons: dict = {}
    for p in picks:
        for r in _CASES[p][2]:
            want_reasons[r] = want_reasons.get(r, 0) + 1

    q = QuarantineWriter(None)
    got = list(stream_fastq(io.StringIO(corpus), q, source="fuzz"))
    assert len(got) == want_yield
    assert q.counts == want_reasons
    # every record parses into the engine alphabet
    for name, seq, phreds in got:
        assert seq.dtype == np.int8 and seq.min() >= 0 and seq.max() <= 3
        assert len(phreds) == len(seq) and phreds.min() >= 0


def test_fastq_truncated_tail_quarantined_or_tolerated(tmp_path):
    text = "@a\nACGT\n+\nIIII\n@tail\nAC\n"
    q = QuarantineWriter(str(tmp_path / "q.jsonl"))
    got = list(stream_fastq(io.StringIO(text), q, source="t.fastq"))
    assert [r[0] for r in got] == ["a"]
    assert q.counts == {"truncated": 1}
    q.close()
    entries = [json.loads(l) for l in open(q.path)]
    assert entries[0]["reason"] == "truncated"
    assert entries[0]["source"] == "t.fastq"
    # watch mode: the tail is a file still being written — silence
    q2 = QuarantineWriter(None)
    assert [r[0] for r in
            stream_fastq(io.StringIO(text), q2, tolerate_tail=True)
            ] == ["a"]
    assert q2.counts == {}


def test_fastq_gzip_midstream_eof_quarantined_not_fatal(tmp_path):
    payload = "".join(f"@r{i}\nACGTACGT\n+\nIIIIIIII\n"
                      for i in range(50)).encode()
    blob = gzip.compress(payload)
    cut = tmp_path / "cut.fastq.gz"
    cut.write_bytes(blob[: len(blob) // 2])
    q = QuarantineWriter(None)
    got = list(stream_fastq(str(cut), q))
    # some prefix decodes; the EOF mid-stream is a typed quarantine
    # entry, not an exception
    assert len(got) < 50
    assert q.counts.get("truncated") == 1


def test_jsonl_fuzz_bad_lines_quarantined():
    lines = ['{"id": "a"}', "not json", "[1, 2]", "", '{"id": "b"}',
             '{"id": "c"', "42"]
    q = QuarantineWriter(None)
    got = list(stream_jsonl(lines, q, source="reqs.jsonl"))
    assert [o["id"] for o in got] == ["a", "b"]
    assert q.counts == {"malformed_record": 4}


def test_ingest_fault_site_error_quarantines_crash_propagates():
    from rifraf_tpu.serve.faults import FaultPlan, InjectedCrashError

    text = "@a\nACG\n+\nIII\n@b\nACG\n+\nIII\n"
    q = QuarantineWriter(None)
    got = list(stream_fastq(io.StringIO(text), q,
                            faults=FaultPlan.parse("ingest:error:n=1")))
    assert [r[0] for r in got] == ["b"]
    assert q.counts == {"injected_fault": 1}
    # kind="crash" must NOT be contained — it is the simulated process
    # death the journal/resume machinery exists for
    with pytest.raises(InjectedCrashError):
        list(stream_fastq(io.StringIO(text), QuarantineWriter(None),
                          faults=FaultPlan.parse("ingest:crash")))


def test_cluster_grouping_by_name_prefix():
    assert cluster_key("c1/r5") == "c1"
    assert cluster_key("solo") == "solo"
    recs = [("c1/r1", np.zeros(3, np.int8), np.zeros(3, np.int8)),
            ("c1/r2", np.zeros(3, np.int8), np.zeros(3, np.int8)),
            ("c2/r1", np.zeros(3, np.int8), np.zeros(3, np.int8))]
    groups = list(group_clusters(iter(recs)))
    assert [(g[0], len(g[1])) for g in groups] == [("c1", 2), ("c2", 1)]


def test_sidecar_paths():
    assert quarantine_path_for("/d/in.fastq.gz") == \
        "/d/in.quarantine.jsonl"
    assert quarantine_path_for("/d/in.jsonl") == "/d/in.quarantine.jsonl"
    assert journal_path_for("/d/in.fq") == "/d/in.journal.jsonl"


# --------------------------------------------------------------- journal


def test_journal_append_is_fsyncd_and_torn_tail_recovered(tmp_path):
    p = str(tmp_path / "j.jsonl")
    j, prior = open_resumable(p, {"fingerprint": "f1"}, resume=False)
    assert prior == []
    j.append({"kind": "chunk", "task": 0})
    j.append({"kind": "chunk", "task": 1})
    j.close()
    # a kill mid-append leaves a torn trailing line
    with open(p, "ab") as fh:
        fh.write(b'{"kind": "chu')
    records, torn = read_journal(p)
    assert torn and [r["kind"] for r in records] == \
        ["header", "chunk", "chunk"]
    # resuming re-anchors at the last complete record and appends clean
    j2, prior = open_resumable(p, {"fingerprint": "f1"}, resume=True)
    assert [r["task"] for r in prior] == [0, 1]
    j2.append({"kind": "chunk", "task": 2})
    j2.close()
    records, torn = read_journal(p)
    assert not torn and [r.get("task") for r in records[1:]] == [0, 1, 2]


def test_journal_fingerprint_mismatch_refused(tmp_path):
    p = str(tmp_path / "j.jsonl")
    j, _ = open_resumable(p, {"fingerprint": "f1"}, resume=False)
    j.close()
    with pytest.raises(JournalError, match="fingerprint"):
        open_resumable(p, {"fingerprint": "OTHER"}, resume=True)
    # without resume the journal is simply restarted
    j2, prior = open_resumable(p, {"fingerprint": "OTHER"}, resume=False)
    j2.close()
    assert prior == [] and read_journal(p)[0][0]["fingerprint"] == "OTHER"


def test_fingerprint_stable_and_discriminating():
    a = fingerprint(1, [("x", 2)], "bucketed")
    assert a == fingerprint(1, [("x", 2)], "bucketed")
    assert a != fingerprint(1, [("x", 3)], "bucketed")


def test_sweep_content_digest_sees_content_not_just_shapes():
    """The sweep resume fingerprint must distinguish clusters whose
    SHAPES match but whose read/phred content or error model differs —
    shape-only fingerprints would let --resume silently mix results
    journaled under a different configuration."""
    import dataclasses

    from rifraf_tpu.models.errormodel import Scores
    from rifraf_tpu.models.sequences import make_read_scores
    from rifraf_tpu.parallel.sweep_sharded import _content_digest
    from rifraf_tpu.utils.phred import phred_to_log_p

    scores = Scores(-3.0, -4.0, -4.0)

    def mk(seq, phred=20):
        log_p = phred_to_log_p(np.full(len(seq), float(phred)))
        return make_read_scores(seq, log_p, 5, scores)

    base = [[mk("ACGTACGT"), mk("ACGTACGT")]]
    assert _content_digest(base) == \
        _content_digest([[mk("ACGTACGT"), mk("ACGTACGT")]])
    # same lengths, different base content
    assert _content_digest(base) != _content_digest(
        [[mk("ACGTACGA"), mk("ACGTACGT")]])
    # same sequences, different phreds
    assert _content_digest(base) != _content_digest(
        [[mk("ACGTACGT", phred=30), mk("ACGTACGT")]])
    # same reads, different error-model scores
    swapped = [[dataclasses.replace(r, scores=Scores(-2.0, -4.0, -4.0))
                for r in base[0]]]
    assert _content_digest(base) != _content_digest(swapped)
    # cluster boundaries matter: [r1, r2] vs [r1], [r2]
    assert _content_digest([[mk("ACGT"), mk("ACGT")]]) != \
        _content_digest([[mk("ACGT")], [mk("ACGT")]])


# ----------------------------------------------------- watch-spool rules


def test_watch_candidates_filtering():
    from rifraf_tpu.cli.serve import watch_candidates

    names = ["a.jsonl", "b.fastq", "c.fq", "d.fastq.gz",
             ".hidden.jsonl", "e.jsonl.tmp", "f.tmp.jsonl",
             "a.out.jsonl", "a.quarantine.jsonl", "a.journal.jsonl",
             "notes.txt"]
    assert watch_candidates(names) == \
        ["a.jsonl", "b.fastq", "c.fq", "d.fastq.gz"]


def test_load_file_journal(tmp_path):
    from rifraf_tpu.cli.serve import _load_file_journal

    path = str(tmp_path / "in.jsonl")
    jp = journal_path_for(path)
    fp = fingerprint("in.jsonl")
    with Journal(jp, header={"fingerprint": fp}) as j:
        j.append({"kind": "req", "id": "q0"})
        j.append({"kind": "req", "id": "q1"})
    done, finished = _load_file_journal(path, resume=True, fp=fp)
    assert done == {"q0", "q1"} and not finished
    with Journal(jp, resume=True) as j:
        j.append({"kind": "done", "n": 2})
    done, finished = _load_file_journal(path, resume=True, fp=fp)
    assert finished
    # a stale journal (file rewritten / config changed => fingerprint
    # mismatch) is dropped: re-serve from scratch, don't skip new work
    assert _load_file_journal(path, resume=True, fp="OTHER") == \
        (set(), False)
    # resume off: prior journals are ignored
    assert _load_file_journal(path, resume=False) == (set(), False)


def test_spool_fingerprint_tracks_config_and_content(tmp_path):
    """The watch journal fingerprint must change when the spool file is
    rewritten (same name, different content) or the serve config
    (error model, phred cap, deadline) changes — but stay stable under
    pure append-growth of a large spool."""
    from rifraf_tpu.cli.serve import (
        _spool_fingerprint,
        build_parser,
        config_from_args,
    )

    path = tmp_path / "in.jsonl"
    path.write_text('{"id": "a"}\n')

    def fp(*argv):
        args = build_parser().parse_args(list(argv))
        return _spool_fingerprint(str(path), args, config_from_args(args))

    base = fp()
    assert base == fp()
    assert base != fp("--seq-errors", "3,1,1")
    assert base != fp("--phred-cap", "30")
    assert base != fp("--deadline-ms", "100")
    # rewritten under the same name: different fingerprint
    path.write_text('{"id": "ZZ"}\n')
    assert fp() != base
    # append-growth past the 64 KiB head window: fingerprint stable
    path.write_text("x" * 70000)
    grown = fp()
    with open(path, "a") as fh:
        fh.write("y" * 1000)
    assert fp() == grown


# ------------------------------------------------- resume grid (slow)


def _tiny_clusters(n=5, nseqs=4, length=40, seed=0):
    from rifraf_tpu.engine.params import RifrafParams
    from rifraf_tpu.models.errormodel import ErrorModel
    from rifraf_tpu.models.sequences import make_read_scores
    from rifraf_tpu.sim.sample import sample_sequences
    from rifraf_tpu.utils.phred import phred_to_log_p

    rng = np.random.default_rng(seed)
    params = RifrafParams()
    seq_errors = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)
    clusters = []
    for _ in range(n):
        _, _, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=nseqs, length=length, error_rate=0.03, rng=rng,
            seq_errors=seq_errors,
        )
        clusters.append([
            make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                             params.bandwidth, params.scores)
            for s, p in zip(seqs, phreds)
        ])
    return clusters


# small chunks + no lane coalescing => several checkpointable chunks
_SWEEP_KW = dict(cluster_chunk=2, lane_target=0, segment_pack=False)


def _assert_results_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert np.array_equal(ra.consensus, rb.consensus)
        assert float(ra.score) == float(rb.score)
        assert int(ra.n_iters) == int(rb.n_iters)
        assert bool(ra.converged) == bool(rb.converged)


@pytest.mark.slow
def test_sweep_resume_after_crash_recomputes_one_interval(
        monkeypatch, tmp_path):
    """Crash the sweep after chunk 1 of 3; --resume must produce
    bit-identical results while recomputing only the un-journaled
    chunks (<= one checkpoint interval beyond the completed set)."""
    from rifraf_tpu.parallel.sweep_sharded import (
        ChunkExecutor,
        sweep_clusters_sharded,
    )

    clusters = _tiny_clusters()
    reference = sweep_clusters_sharded(clusters, **_SWEEP_KW)

    jp = str(tmp_path / "sweep.journal.jsonl")
    orig_collect = ChunkExecutor.collect
    state = {"n": 0}

    def crashing(self, handle):
        if state["n"] >= 1:
            raise RuntimeError("injected mid-sweep death")
        state["n"] += 1
        return orig_collect(self, handle)

    monkeypatch.setattr(ChunkExecutor, "collect", crashing)
    with pytest.raises(RuntimeError, match="mid-sweep death"):
        sweep_clusters_sharded(clusters, journal_path=jp, **_SWEEP_KW)
    records, _ = read_journal(jp)
    n_journaled = sum(r.get("kind") == "chunk" for r in records)
    assert n_journaled == 1  # the fsync'd checkpoint survived the crash

    counted = {"n": 0}

    def counting(self, handle):
        counted["n"] += 1
        return orig_collect(self, handle)

    monkeypatch.setattr(ChunkExecutor, "collect", counting)
    resumed = sweep_clusters_sharded(clusters, journal_path=jp,
                                     resume=True, **_SWEEP_KW)
    _assert_results_equal(reference, resumed)

    records, _ = read_journal(jp)
    chunk_tasks = [r["task"] for r in records if r.get("kind") == "chunk"]
    assert len(chunk_tasks) == len(set(chunk_tasks))  # no recompute
    assert counted["n"] == len(chunk_tasks) - n_journaled
    # mismatched parameters refuse to resume rather than mixing results
    with pytest.raises(JournalError, match="fingerprint"):
        sweep_clusters_sharded(clusters, journal_path=jp, resume=True,
                               cluster_chunk=3, lane_target=0,
                               segment_pack=False)
    # edited CONTENT with identical shapes must also refuse: the shape
    # facts alone cannot tell these inputs from the journaled ones
    import dataclasses

    edited = [list(c) for c in clusters]
    r0 = edited[0][0]
    lp = r0.error_log_p.copy()
    lp[0] -= 0.1
    edited[0][0] = dataclasses.replace(r0, error_log_p=lp)
    with pytest.raises(JournalError, match="fingerprint"):
        sweep_clusters_sharded(edited, journal_path=jp, resume=True,
                               **_SWEEP_KW)


_KILL_CHILD = r"""
import os, signal, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, {repo!r})
sys.path.insert(0, {testdir!r})
from test_durability import _tiny_clusters, _SWEEP_KW
from rifraf_tpu.io import journal as jmod
from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded

orig_append = jmod.Journal.append
def append_then_die(self, record):
    orig_append(self, record)
    if record.get("kind") == "chunk":
        os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, no atexit
jmod.Journal.append = append_then_die
sweep_clusters_sharded(_tiny_clusters(), journal_path={jp!r}, **_SWEEP_KW)
"""


@pytest.mark.slow
def test_sweep_resume_after_sigkill_bit_identical(tmp_path):
    """The acceptance scenario end to end: SIGKILL the sweep process
    the instant its first chunk checkpoint hits the journal, then
    resume in a fresh context — outputs bit-identical, completed work
    not recomputed."""
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded

    jp = str(tmp_path / "sweep.journal.jsonl")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child = _KILL_CHILD.format(repo=repo,
                               testdir=os.path.join(repo, "tests"),
                               jp=jp)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=540)
    assert proc.returncode == -signal.SIGKILL, proc.stderr[-2000:]
    records, torn = read_journal(jp)
    chunk_records = [r for r in records if r.get("kind") == "chunk"]
    assert len(chunk_records) == 1  # the fsync beat the SIGKILL

    clusters = _tiny_clusters()
    reference = sweep_clusters_sharded(clusters, **_SWEEP_KW)
    resumed = sweep_clusters_sharded(clusters, journal_path=jp,
                                     resume=True, **_SWEEP_KW)
    _assert_results_equal(reference, resumed)
    records, _ = read_journal(jp)
    tasks = [r["task"] for r in records if r.get("kind") == "chunk"]
    # at most one checkpoint interval recomputed: the killed run's
    # completed chunk is NOT re-journaled
    assert len(tasks) == len(set(tasks))


# ------------------------------------------------ serve CLI spool (slow)


def _write_reqs(path, ids, seqs=("ACGTACGTACGTACGTACGTACGT",) * 3,
                newline=True):
    lines = [json.dumps({"id": i, "seqs": list(seqs),
                         "phreds": [[20] * len(s) for s in seqs]})
             for i in ids]
    path.write_text("\n".join(lines) + ("\n" if newline else ""))


@pytest.mark.slow
def test_cli_watch_hardening_and_quarantine(tmp_path):
    """One watch-once pass over a hostile spool directory: tmp files
    and dotfiles ignored, a partial trailing JSONL line quarantined as
    truncated (complete lines still served), and a FASTQ spool with a
    malformed record served through the quarantine front door."""
    from rifraf_tpu.cli.serve import main as serve_main

    _write_reqs(tmp_path / "in.jsonl", ["q0", "q1"])
    # partial tail: last line has no newline terminator
    _write_reqs(tmp_path / "partial.jsonl", ["p0", "p1"], newline=False)
    (tmp_path / "skip.jsonl.tmp").write_text('{"id": "nope"}\n')
    (tmp_path / ".hidden.jsonl").write_text('{"id": "nope"}\n')
    fastq = (
        "@c1/r1\nACGTACGTACGTACGTACGTACGT\n+\n" + "I" * 24 + "\n"
        "@c1/r2\nACGTACGTACGTACGTACGTACGT\n+\n" + "I" * 24 + "\n"
        "@badrec\nACGTN\n+\nIIIII\n"
        "@c2/r1\nACGTACGTACGTACGTACGTACGT\n+\n" + "I" * 24 + "\n"
    )
    (tmp_path / "reads.fastq").write_text(fastq)

    rc = serve_main(["--watch", str(tmp_path), "--watch-once",
                     "--max-iters", "8", "--max-batch", "2"])
    assert rc == 0

    by_id = {d["id"]: d for d in (
        json.loads(l) for l in
        (tmp_path / "in.out.jsonl").read_text().splitlines())}
    assert by_id["q0"]["ok"] and by_id["q1"]["ok"]
    # ignored spool members produced no sidecars at all
    assert not (tmp_path / "skip.out.jsonl").exists()
    assert not (tmp_path / ".hidden.out.jsonl").exists()

    # partial file: p0 (complete line) served; the torn p1 line is
    # quarantined as truncated, not parsed, not crashed on
    partial = {d["id"]: d for d in (
        json.loads(l) for l in
        (tmp_path / "partial.out.jsonl").read_text().splitlines())}
    assert partial["p0"]["ok"] and "p1" not in partial
    qents = [json.loads(l) for l in
             (tmp_path / "partial.quarantine.jsonl").read_text()
             .splitlines()]
    assert qents[0]["reason"] == "truncated"

    # FASTQ spool: per-cluster responses; the malformed record is in
    # quarantine with its typed reason
    fq = {d["id"]: d for d in (
        json.loads(l) for l in
        (tmp_path / "reads.out.jsonl").read_text().splitlines())}
    assert fq["c1"]["ok"] and fq["c2"]["ok"]
    assert fq["c1"]["consensus"] == "ACGTACGTACGTACGTACGTACGT"
    fqq = [json.loads(l) for l in
           (tmp_path / "reads.quarantine.jsonl").read_text().splitlines()]
    assert [e["reason"] for e in fqq] == ["bad_alphabet"]
    assert fqq[0]["name"] == "badrec"

    # every served file carries a completion journal ending in "done"
    jrecs = [json.loads(l) for l in
             (tmp_path / "in.journal.jsonl").read_text().splitlines()]
    assert jrecs[0]["kind"] == "header"
    assert {r["id"] for r in jrecs if r["kind"] == "req"} == {"q0", "q1"}
    assert jrecs[-1]["kind"] == "done"


@pytest.mark.slow
def test_cli_watch_resume_skips_journaled_requests(tmp_path):
    """--resume replays the journal sidecar a killed run left behind:
    completed ids are skipped, their outputs preserved, and only the
    remainder is computed (appended)."""
    from rifraf_tpu.cli.serve import (
        _spool_fingerprint,
        build_parser,
        config_from_args,
    )
    from rifraf_tpu.cli.serve import main as serve_main

    _write_reqs(tmp_path / "in.jsonl", ["q0", "q1", "q2"])
    # fabricate the post-kill state: q0 journaled + its output flushed
    argv = ["--watch", str(tmp_path), "--watch-once", "--resume",
            "--max-iters", "8", "--max-batch", "2"]
    args = build_parser().parse_args(argv)
    fp = _spool_fingerprint(str(tmp_path / "in.jsonl"), args,
                            config_from_args(args))
    jp = journal_path_for(str(tmp_path / "in.jsonl"))
    with Journal(jp, header={"fingerprint": fp}) as j:
        j.append({"kind": "req", "id": "q0"})
    sentinel = {"id": "q0", "ok": True, "consensus": "SENTINEL"}
    (tmp_path / "in.out.jsonl").write_text(json.dumps(sentinel) + "\n")

    rc = serve_main(["--watch", str(tmp_path), "--watch-once",
                     "--resume", "--max-iters", "8", "--max-batch", "2"])
    assert rc == 0
    lines = [json.loads(l) for l in
             (tmp_path / "in.out.jsonl").read_text().splitlines()]
    # q0 NOT recomputed: its pre-crash output line is intact
    assert lines[0] == sentinel
    assert {d["id"] for d in lines[1:]} == {"q1", "q2"}
    assert all(d["ok"] for d in lines[1:])
    jrecs = [json.loads(l) for l in open(jp)]
    req_ids = [r["id"] for r in jrecs if r.get("kind") == "req"]
    assert sorted(req_ids) == ["q0", "q1", "q2"]
    assert len(req_ids) == len(set(req_ids))
    assert jrecs[-1]["kind"] == "done"

    # a second resume pass is a no-op: the file is marked done
    rc = serve_main(["--watch", str(tmp_path), "--watch-once",
                     "--resume", "--max-iters", "8", "--max-batch", "2"])
    assert rc == 0
    assert len((tmp_path / "in.out.jsonl").read_text().splitlines()) == 3


@pytest.mark.slow
def test_cli_watch_stale_journal_reserved_not_skipped(tmp_path):
    """A journal left by a DIFFERENT file under the same name (deleted
    and rewritten spool) or a different serve config must not match:
    the file is re-served from scratch instead of its new requests
    being silently skipped against stale journal ids."""
    from rifraf_tpu.cli.serve import main as serve_main

    _write_reqs(tmp_path / "in.jsonl", ["q0", "q1"])
    jp = journal_path_for(str(tmp_path / "in.jsonl"))
    # a stale journal: fingerprint of some other file/config epoch that
    # claims q0 and q1 are already done
    with Journal(jp, header={"fingerprint": "stale-epoch"}) as j:
        j.append({"kind": "req", "id": "q0"})
        j.append({"kind": "req", "id": "q1"})
        j.append({"kind": "done", "n": 2})
    (tmp_path / "in.out.jsonl").write_text('{"id": "q0", "ok": true}\n')

    rc = serve_main(["--watch", str(tmp_path), "--watch-once",
                     "--resume", "--max-iters", "8", "--max-batch", "2"])
    assert rc == 0
    lines = [json.loads(l) for l in
             (tmp_path / "in.out.jsonl").read_text().splitlines()]
    # both requests recomputed; the stale output was truncated
    assert {d["id"] for d in lines} == {"q0", "q1"}
    assert all(d["ok"] and "consensus" in d for d in lines)
    jrecs = [json.loads(l) for l in open(jp)]
    assert jrecs[0]["fingerprint"] != "stale-epoch"


@pytest.mark.slow
def test_watch_repoll_does_not_duplicate_failed_responses(tmp_path):
    """Re-polling a size-stable file whose tail lacks a newline must
    not re-serve (and re-append duplicate ok:false lines for) requests
    that already failed this process — while leaving failures
    un-journaled so a post-crash --resume retries them."""
    from rifraf_tpu.cli.serve import (
        _WatchedFile,
        _serve_watched_jsonl,
        build_parser,
        config_from_args,
    )
    from rifraf_tpu.serve import ConsensusServer

    args = build_parser().parse_args(
        ["--watch", str(tmp_path), "--max-iters", "8",
         "--max-batch", "2"])
    config = config_from_args(args)
    path = tmp_path / "in.jsonl"
    good = json.dumps({"id": "q0",
                       "seqs": ["ACGTACGTACGTACGTACGTACGT"] * 3,
                       "phreds": [[20] * 24] * 3})
    bad = json.dumps({"id": "b0", "seqs": ["ACGT"]})  # no phreds/quals
    path.write_text(good + "\n" + bad + "\n" + '{"id": "tail"')

    server = ConsensusServer(config)
    try:
        wf = _WatchedFile(str(path), False, args, config)
        wf.open_sinks(False)
        assert not _serve_watched_jsonl(wf, server, args, config,
                                        final=False)
        assert not _serve_watched_jsonl(wf, server, args, config,
                                        final=False)
        wf.close_sinks()
    finally:
        server.close()
    lines = [json.loads(l) for l in
             (tmp_path / "in.out.jsonl").read_text().splitlines()]
    # exactly one response per complete line across BOTH polls
    assert sorted(d["id"] for d in lines) == ["b0", "q0"]
    assert not next(d for d in lines if d["id"] == "b0")["ok"]
    # the failure is not journaled: a --resume run would retry it
    jrecs = [json.loads(l) for l in open(journal_path_for(str(path)))]
    assert {r["id"] for r in jrecs if r.get("kind") == "req"} == {"q0"}
