"""Fast read-axis mesh identity tests: the sharded fused step and the
per-device fleet vs their single-device equivalents.

Everything here runs on the 8-virtual-device CPU mesh (tests/conftest.py
forces XLA_FLAGS=--xla_force_host_platform_device_count=8) and stays in
the fast tier — XLA engines only, tiny shapes. The sharded PALLAS launch
path (mesh_fused_step_pallas through engine.realign) is covered by the
slow interpret-mode test in tests/test_pallas_driver.py.

Identity conventions (tests/test_parallel.py): per-lane outputs and
max-unions compare EXACTLY (they never cross a shard boundary); reduced
quantities — the psum'd totals and segment tables — compare at rtol
1e-12, since an 8-way partial-sum tree may reassociate the f64
additions.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax
from rifraf_tpu.ops.fused import fused_step_segmented
from rifraf_tpu.parallel.sharding import make_mesh, mesh_fused_step_segmented
from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _clusters(n_clusters, nseqs=4, length=30, seed=0):
    rng = np.random.default_rng(seed)
    params = RifrafParams()
    out = []
    for _ in range(n_clusters):
        _, _, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=nseqs, length=length, error_rate=0.03, rng=rng,
            seq_errors=SEQ_ERRORS,
        )
        out.append([
            make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                             params.bandwidth, params.scores)
            for s, p in zip(seqs, phreds)
        ])
    return out


def _packed_problem(n_seg, npad, seed=7):
    """A segment-packed lane block: ``n_seg`` problems' reads in ``npad``
    lanes, pad lanes duplicating slot 0's first read at weight 0 (the
    executor's padding convention). Returns the fused_step_segmented
    argument tuple (minus K/n_seg) plus K."""
    clusters = _clusters(n_seg, nseqs=3, length=24 + 6 * n_seg, seed=seed)
    tlens = [len(c[0]) for c in clusters]
    Tmax = max(tlens) + 8
    tmpl = np.zeros((n_seg, Tmax), np.int8)
    for s, c in enumerate(clusters):
        tmpl[s, : tlens[s]] = c[0].seq

    reads, seg_ids, bws = [], [], []
    for s, c in enumerate(clusters):
        reads.extend(c)
        seg_ids.extend([s] * len(c))
        bws.extend(r.bandwidth for r in c)
    n_live = len(reads)
    pad = npad - n_live
    assert pad >= 0
    reads += [clusters[0][0]] * pad
    seg_ids += [0] * pad
    bws += [clusters[0][0].bandwidth] * pad
    weights = np.asarray([1.0] * n_live + [0.0] * pad, np.float64)
    L = max(len(r) for r in reads) + 4
    b = batch_reads(reads, max_len=L, dtype=np.float64)

    lane_tlens = np.asarray(tlens, np.int32)[np.asarray(seg_ids)]
    geom = align_jax.BandGeometry.make(
        jnp.asarray(b.lengths), jnp.asarray(lane_tlens),
        jnp.asarray(bws, np.int32),
    )
    K = int(np.asarray(geom.nd).max() + np.asarray(geom.offset).max())
    K = ((K + 7) // 8) * 8
    args = (
        jnp.asarray(tmpl), jnp.asarray(tlens, np.int32),
        jnp.asarray(seg_ids, np.int32), jnp.asarray(b.seq),
        jnp.asarray(b.match), jnp.asarray(b.mismatch),
        jnp.asarray(b.ins), jnp.asarray(b.dels), jnp.asarray(b.lengths),
        jnp.asarray(bws, np.int32), jnp.asarray(weights),
    )
    return args, K


@pytest.mark.parametrize("want_stats", [False, True])
@pytest.mark.parametrize("n_seg", [1, 3])
def test_mesh_fused_step_segmented_matches_single(n_seg, want_stats):
    """The shard_map-wrapped segmented fused step over the 8-device mesh
    vs the single-device call: n_seg=1 is the whole-block layout (every
    lane one segment), n_seg=3 the segment-packed one. Per-lane scores,
    error counts, and the pmax'd edits union are exact; the psum'd
    totals and segment tables agree at 1e-12."""
    args, K = _packed_problem(n_seg, npad=16)
    single = fused_step_segmented(*args, K, n_seg, want_stats=want_stats)
    mesh = make_mesh(8)
    sharded = mesh_fused_step_segmented(
        mesh, *args, K=K, n_seg=n_seg, want_stats=want_stats)

    np.testing.assert_array_equal(
        np.asarray(sharded["scores"]), np.asarray(single["scores"]))
    for name in ("total", "sub", "ins", "del"):
        np.testing.assert_allclose(
            np.asarray(sharded[name]), np.asarray(single[name]),
            rtol=1e-12, atol=0, err_msg=name)
    if want_stats:
        np.testing.assert_array_equal(
            np.asarray(sharded["n_errors"]), np.asarray(single["n_errors"]))
        np.testing.assert_array_equal(
            np.asarray(sharded["edits"]), np.asarray(single["edits"]))


@pytest.mark.parametrize("dap", [False, True])
def test_sweep_mesh_matches_unsharded(dap):
    """End-to-end bit identity through the sweep executor: the same
    clusters swept over the 8-device mesh and unsharded, under both
    do_alignment_proposals settings (the edits-gated and all-edits
    candidate paths)."""
    clusters = _clusters(3, seed=3)
    base = sweep_clusters_sharded(clusters, do_alignment_proposals=dap)
    mesh = sweep_clusters_sharded(clusters, mesh=make_mesh(8),
                                  do_alignment_proposals=dap)
    for g, (a, b) in enumerate(zip(base, mesh)):
        assert np.array_equal(a.consensus, b.consensus), g
        assert np.isclose(a.score, b.score, rtol=1e-9), g
        assert a.n_iters == b.n_iters, g


@pytest.mark.parametrize("segment_pack", [False, True])
def test_sweep_mesh_two_devices_scaling_sane(segment_pack):
    """2-device scaling sanity for CI's multidevice job: a 2-device
    submesh must produce the single-device answer on both the
    segment-packed and whole-block layouts, and the mesh plan must keep
    every cluster accounted for."""
    clusters = _clusters(4, seed=9)
    base = sweep_clusters_sharded(clusters, segment_pack=segment_pack)
    mesh = sweep_clusters_sharded(clusters, mesh=make_mesh(2),
                                  segment_pack=segment_pack)
    assert len(mesh) == len(clusters)
    for g, (a, b) in enumerate(zip(base, mesh)):
        assert np.array_equal(a.consensus, b.consensus), g
        assert np.isclose(a.score, b.score, rtol=1e-9), g


def test_sweep_fleet_matches_single_worker():
    """The per-device fleet (n_workers executors, chunks dealt
    round-robin) returns bit-identical results to one worker: the
    executors share one trace per bucket signature, only the placement
    differs."""
    clusters = _clusters(6, seed=5)
    one = sweep_clusters_sharded(clusters, n_workers=1)
    fleet = sweep_clusters_sharded(clusters, n_workers=3)
    for g, (a, b) in enumerate(zip(one, fleet)):
        assert np.array_equal(a.consensus, b.consensus), g
        assert a.score == b.score, g
        assert a.n_iters == b.n_iters, g
        assert a.converged == b.converged, g


def test_sweep_fleet_rejects_mesh():
    with pytest.raises(ValueError, match="fleet"):
        sweep_clusters_sharded(_clusters(1), mesh=make_mesh(2),
                               n_workers=2)


def test_serve_fleet_matches_single_worker():
    """N serving workers on the shared flush queue == 1 worker, result
    for result — the fleet only changes which device executes a flush,
    never what it computes."""
    from rifraf_tpu.serve import ServeConfig, submit_many

    clusters = _clusters(5, seed=11)
    single = submit_many(clusters,
                         ServeConfig(max_wait_ms=2.0, n_workers=1))
    fleet = submit_many(clusters,
                        ServeConfig(max_wait_ms=2.0, n_workers=3))
    assert all(r.ok for r in single)
    assert all(r.ok for r in fleet)
    for g, (a, b) in enumerate(zip(single, fleet)):
        assert np.array_equal(a.consensus, b.consensus), g
        assert a.score == b.score, g


def test_serve_fleet_health_and_close():
    from rifraf_tpu.serve import ConsensusServer, ServeConfig

    server = ConsensusServer(ServeConfig(n_workers=2))
    try:
        h = server.health()
        assert h["n_workers"] == 2
        assert h["worker_alive"]
        assert len(h["workers"]) == 2
    finally:
        server.close()
    h = server.health()
    assert h["closed"]
    assert not h["worker_alive"]  # every worker consumed its STOP


def test_serve_fleet_rejects_mesh():
    from rifraf_tpu.serve import ConsensusServer, ServeConfig

    with pytest.raises(ValueError, match="fleet"):
        ConsensusServer(ServeConfig(n_workers=2, mesh=make_mesh(2)))


def test_mesh_round_and_axis_size():
    from rifraf_tpu.utils.meshutil import mesh_axis_size, mesh_round

    assert mesh_axis_size(None) == 1
    mesh = make_mesh(8)
    assert mesh_axis_size(mesh) == 8
    assert mesh_round(5, None) == 5
    assert mesh_round(5, mesh) == 8
    assert mesh_round(5, None, pow2=True) == 8
    assert mesh_round(9, mesh, pow2=True) == 16
    assert mesh_round(8, mesh, pow2=True) == 8
