"""Vectorized numpy engine vs the cell-by-cell oracle (codon-capable)."""

import numpy as np
import pytest

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.ops import align_np

SCORES = Scores.from_error_model(ErrorModel(1.0, 5.0, 5.0))
CODON_SCORES = Scores.from_error_model(ErrorModel(2.0, 0.5, 0.5, 3.0, 3.0))


def random_case(rng, slen, tlen, bw, scores):
    t = rng.integers(0, 4, size=tlen).astype(np.int8)
    s = rng.integers(0, 4, size=slen).astype(np.int8)
    log_p = rng.uniform(-3.0, -0.5, size=slen)
    return t, make_read_scores(s, log_p, bw, scores)


@pytest.mark.parametrize("use_codon", [False, True])
@pytest.mark.parametrize("trim,skew", [(False, False), (True, False), (False, True)])
def test_forward_vec_matches_cell_loop(use_codon, trim, skew):
    rng = np.random.default_rng(11 + use_codon)
    scores = CODON_SCORES if use_codon else SCORES
    for _ in range(8):
        slen = int(rng.integers(5, 40))
        tlen = int(rng.integers(5, 40))
        bw = int(rng.integers(3, 10))
        t, rs = random_case(rng, slen, tlen, bw, scores)
        want = align_np.forward(t, rs, trim=trim, skew_matches=skew)
        got = align_np.forward_vec(t, rs, trim=trim, skew_matches=skew)
        np.testing.assert_allclose(
            got.dense(default=-np.inf),
            want.dense(default=-np.inf),
            rtol=1e-9, atol=1e-9,
            err_msg=f"slen={slen} tlen={tlen} bw={bw} codon={use_codon}",
        )


@pytest.mark.parametrize("use_codon", [False, True])
def test_backward_vec_matches_cell_loop(use_codon):
    rng = np.random.default_rng(23 + use_codon)
    scores = CODON_SCORES if use_codon else SCORES
    for _ in range(5):
        slen = int(rng.integers(5, 35))
        tlen = int(rng.integers(5, 35))
        t, rs = random_case(rng, slen, tlen, 6, scores)
        want = align_np.backward(t, rs)
        got = align_np.backward_vec(t, rs)
        np.testing.assert_allclose(
            got.dense(default=-np.inf),
            want.dense(default=-np.inf),
            rtol=1e-9, atol=1e-9,
        )


@pytest.mark.parametrize("use_codon", [False, True])
def test_moves_vec_produce_optimal_paths(use_codon):
    """Traceback from the vectorized move matrix is a complete optimal path
    (moves may differ from the cell loop only at exact ties)."""
    rng = np.random.default_rng(37 + use_codon)
    scores = CODON_SCORES if use_codon else SCORES
    for _ in range(6):
        slen = int(rng.integers(8, 30))
        tlen = int(rng.integers(8, 30))
        t, rs = random_case(rng, slen, tlen, 6, scores)
        A, moves = align_np.forward_moves_vec(t, rs)
        path = align_np.backtrace(moves)
        at, as_ = align_np.moves_to_aligned_seqs(path, t, rs.seq)
        assert (as_[as_ >= 0] == rs.seq).all()
        assert (at[at >= 0] == t).all()
        # replay the path score; must equal the DP total
        total = 0.0
        i = j = 0
        for m in path:
            di, dj = align_np.OFFSETS[m]
            i, j = i + di, j + dj
            if m == align_np.TRACE_MATCH:
                total += (
                    rs.match_scores[i - 1]
                    if rs.seq[i - 1] == t[j - 1]
                    else rs.mismatch_scores[i - 1]
                )
            elif m == align_np.TRACE_INSERT:
                total += rs.ins_scores[i - 1]
            elif m == align_np.TRACE_DELETE:
                total += rs.del_scores[i]
            elif m == align_np.TRACE_CODON_INSERT:
                total += rs.codon_ins_scores[i - 3]
            elif m == align_np.TRACE_CODON_DELETE:
                total += rs.codon_del_scores[i]
        np.testing.assert_allclose(
            total, A[slen, tlen], rtol=1e-9, atol=1e-9
        )
