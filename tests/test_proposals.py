"""Proposal system tests.

Ports /root/reference/test/test_proposals.jl (apply/ambiguity cases) and the
core property test from test_model.jl:39-153: the O(band) rescoring trick
must exactly equal a full realignment of the edited template — for the numpy
oracle and for the batched JAX scorer.
"""

import zlib

import numpy as np
import pytest

from rifraf_tpu.engine.proposals import (
    AmbiguousProposalsError,
    Deletion,
    Insertion,
    ScoredProposal,
    Substitution,
    apply_proposals,
    choose_candidates,
)
from rifraf_tpu.engine.scoring_np import score_proposal
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_np
from rifraf_tpu.ops.align_jax import backward_batch, forward_batch
from rifraf_tpu.ops.proposal_jax import score_proposals_batch
from rifraf_tpu.utils.constants import decode_seq, encode_seq


def seq(s):
    return encode_seq(s)


class TestApplyProposals:
    """test_proposals.jl:16-33 ported (1-based jl positions shifted)."""

    def test_substitution(self):
        assert decode_seq(apply_proposals(seq("ACG"), [Substitution(1, 3)])) == "ATG"

    def test_insertion_prepend(self):
        assert decode_seq(apply_proposals(seq("ACG"), [Insertion(0, 3)])) == "TACG"

    def test_insertion_middle(self):
        assert decode_seq(apply_proposals(seq("ACG"), [Insertion(1, 3)])) == "ATCG"

    def test_insertion_append(self):
        assert decode_seq(apply_proposals(seq("ACG"), [Insertion(3, 3)])) == "ACGT"

    def test_deletion(self):
        assert decode_seq(apply_proposals(seq("ACG"), [Deletion(1)])) == "AG"

    def test_deletion_then_insertion_same_spot(self):
        # deleting base at pos then inserting after it: the insertion must
        # not re-emit the deleted base (proposals.jl:63-69)
        got = apply_proposals(seq("ACG"), [Deletion(1), Insertion(2, 3)])
        assert decode_seq(got) == "ATG"

    def test_multiple(self):
        got = apply_proposals(
            seq("ACGT"), [Substitution(0, 1), Deletion(2), Insertion(4, 0)]
        )
        assert decode_seq(got) == "CCTA"

    def test_ambiguous_two_subs(self):
        with pytest.raises(AmbiguousProposalsError):
            apply_proposals(seq("ACG"), [Substitution(1, 3), Substitution(1, 0)])

    def test_ambiguous_sub_del(self):
        with pytest.raises(AmbiguousProposalsError):
            apply_proposals(seq("ACG"), [Substitution(1, 3), Deletion(1)])

    def test_ambiguous_two_ins(self):
        with pytest.raises(AmbiguousProposalsError):
            apply_proposals(seq("ACG"), [Insertion(1, 3), Insertion(1, 0)])


def test_choose_candidates_min_dist():
    cands = [
        ScoredProposal(Substitution(0, 1), 5.0),
        ScoredProposal(Substitution(1, 1), 4.0),
        ScoredProposal(Substitution(9, 1), 3.0),
    ]
    chosen = choose_candidates(cands, min_dist=5)
    got = {c.proposal.pos for c in chosen}
    assert got == {0, 9}


SCORES = Scores.from_error_model(ErrorModel(1.0, 5.0, 5.0))
CODON_SCORES = Scores.from_error_model(ErrorModel(2.0, 0.5, 0.5, 1.0, 1.0))


def full_rescore(template, proposal, rs):
    """Oracle: apply the proposal and realign from scratch."""
    new_t = apply_proposals(template, [proposal])
    F = align_np.forward(new_t, rs)
    return F[len(rs), len(new_t)]


def mutate_read(rng, template, sub_p=0.05, indel_p=0.02):
    """Light error process so reads stay near the template (the rescoring
    trick is exact only with an adequately wide band — the reference tests
    with bandwidth >= 30 and low-error reads, test_model.jl:44-66)."""
    out = []
    for b in template:
        r = rng.random()
        if r < indel_p:
            continue  # deletion
        if r < 2 * indel_p:
            out.append(int(rng.integers(0, 4)))  # insertion
        if rng.random() < sub_p:
            out.append(int((b + rng.integers(1, 4)) % 4))
        else:
            out.append(int(b))
    if not out:
        out = [int(template[0])]
    return np.array(out, dtype=np.int8)


def _make_sub(rng, tlen):
    return Substitution(int(rng.integers(0, tlen)), int(rng.integers(0, 4)))


def _make_ins(rng, tlen):
    return Insertion(int(rng.integers(0, tlen + 1)), int(rng.integers(0, 4)))


def _make_del(rng, tlen):
    return Deletion(int(rng.integers(0, tlen)))


def _run_rescoring_property(make_proposal, n_cases, seed,
                            proposals_per_template=4):
    """The exactness property (test_model.jl:39-153), numpy oracle:
    O(band) rescoring of a proposal == full realignment of the edited
    template. Mirrors the reference's conditions — reads drawn near the
    template, bandwidth = max(5 * |len(t) - len(s)|, 30), codon moves
    coin-flipped per case (test_model.jl:47-53). The reference scores one
    proposal per fresh template x read; here each template/read pair
    scores several proposals (the A/B fills are shared; each proposal
    still gets its own from-scratch realignment oracle), keeping the same
    number of scored-proposal comparisons in a fraction of the fills."""
    rng = np.random.default_rng(seed)
    n_templates = (n_cases + proposals_per_template - 1) // proposals_per_template
    done = 0
    for _ in range(n_templates):
        tlen = int(rng.integers(30, 51))
        use_codon = bool(rng.integers(0, 2))
        scores = CODON_SCORES if use_codon else SCORES
        template = rng.integers(0, 4, size=tlen).astype(np.int8)
        s = mutate_read(rng, template)
        log_p = rng.uniform(-2.0, -1.0, size=len(s))
        bandwidth = max(5 * abs(tlen - len(s)), 30)
        rs = make_read_scores(s, log_p, bandwidth, scores)
        A = align_np.forward(template, rs)
        B = align_np.backward(template, rs)
        for _ in range(min(proposals_per_template, n_cases - done)):
            proposal = make_proposal(rng, tlen)
            got = score_proposal(proposal, A, B, template, rs)
            want = full_rescore(template, proposal, rs)
            np.testing.assert_allclose(
                got, want, rtol=1e-9, atol=1e-9,
                err_msg=(f"{proposal} tlen={tlen} slen={len(s)} "
                         f"codon={use_codon}"),
            )
            done += 1


@pytest.mark.parametrize("kind,make_proposal", [
    ("substitution", _make_sub),
    ("insertion", _make_ins),
    ("deletion", _make_del),
])
def test_rescoring_property_1000_random(kind, make_proposal):
    """1000 random cases per proposal type (test_model.jl:86-108)."""
    _run_rescoring_property(make_proposal, 1000, seed=zlib.crc32(kind.encode()))


@pytest.mark.parametrize("kind,make_proposal", [
    ("del_begin", lambda rng, tlen: Deletion(0)),
    ("del_end", lambda rng, tlen: Deletion(tlen - 1)),
    ("sub_begin", lambda rng, tlen: Substitution(0, int(rng.integers(0, 4)))),
    ("sub_end",
     lambda rng, tlen: Substitution(tlen - 1, int(rng.integers(0, 4)))),
    ("ins_begin", lambda rng, tlen: Insertion(0, int(rng.integers(0, 4)))),
    ("ins_end", lambda rng, tlen: Insertion(tlen, int(rng.integers(0, 4)))),
])
def test_rescoring_property_edges(kind, make_proposal):
    """10 cases per edge position kind (test_model.jl:109-153)."""
    _run_rescoring_property(make_proposal, 10, seed=zlib.crc32(kind.encode()),
                            proposals_per_template=1)


@pytest.mark.slow
def test_rescoring_trick_equals_full_realignment_jax():
    """Same property for the batched device scorer (no codon moves)."""
    rng = np.random.default_rng(99)
    tlen = 20
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(4):
        s = mutate_read(rng, template)
        log_p = rng.uniform(-2.0, -1.0, size=len(s))
        reads.append(make_read_scores(s, log_p, 15, SCORES))
    batch = batch_reads(reads, dtype=np.float64)
    A, _, _, geom = forward_batch(template, batch)
    B, _, _ = backward_batch(template, batch)

    proposals = []
    for pos in range(tlen):
        for b in range(4):
            proposals.append(Substitution(pos, b))
    for pos in range(tlen + 1):
        for b in range(4):
            proposals.append(Insertion(pos, b))
    for pos in range(tlen):
        proposals.append(Deletion(pos))

    got = np.asarray(score_proposals_batch(A, B, batch, geom, proposals))
    assert got.shape == (len(reads), len(proposals))
    for k, rs in enumerate(reads):
        for p_idx in range(len(proposals)):  # every proposal, every read
            want = full_rescore(template, proposals[p_idx], rs)
            np.testing.assert_allclose(
                got[k, p_idx], want, rtol=1e-9, atol=1e-9,
                err_msg=f"read {k} proposal {proposals[p_idx]}",
            )


@pytest.mark.slow
def test_jax_scorer_matches_np_scorer():
    """JAX batch scorer vs numpy oracle on every proposal."""
    rng = np.random.default_rng(5)
    tlen = 15
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    s = rng.integers(0, 4, size=18).astype(np.int8)
    log_p = rng.uniform(-3.0, -0.5, size=18)
    rs = make_read_scores(s, log_p, 5, SCORES)
    batch = batch_reads([rs], dtype=np.float64)
    Aj, _, _, geom = forward_batch(template, batch)
    Bj, _, _ = backward_batch(template, batch)
    A = align_np.forward(template, rs)
    B = align_np.backward(template, rs)

    proposals = (
        [Substitution(p, b) for p in range(tlen) for b in range(4)]
        + [Insertion(p, b) for p in range(tlen + 1) for b in range(4)]
        + [Deletion(p) for p in range(tlen)]
    )
    got = np.asarray(score_proposals_batch(Aj, Bj, batch, geom, proposals))[0]
    for k, prop in enumerate(proposals):
        want = score_proposal(prop, A, B, template, rs)
        np.testing.assert_allclose(
            got[k], want, rtol=1e-9, atol=1e-9, err_msg=str(prop)
        )
