"""Oracle tests for the jitted codon-capable reference engine
(ops.align_codon_jax) against the numpy host engine (align_np /
scoring_np), which is itself pinned to the reference's cell loop."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from rifraf_tpu.engine.proposals import Deletion, Insertion, Substitution
from rifraf_tpu.engine.realign import RefAligner
from rifraf_tpu.engine.scoring_np import score_proposal
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.ops import align_codon_jax as acj
from rifraf_tpu.ops import align_np

REF_SCORES = Scores.from_error_model(ErrorModel(10.0, 1e-1, 1e-1, 1.0, 1.0))


def _pair(rng, L):
    tlen = int(rng.integers(max(10, L - 9), L + 10))
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    ref_len = int(rng.integers(max(9, L - 6), L + 7) // 3 * 3)
    ref_seq = rng.integers(0, 4, size=ref_len).astype(np.int8)
    bw = int(rng.integers(5, 12))
    rs = make_read_scores(ref_seq, np.full(ref_len, np.log10(0.1)), bw,
                          REF_SCORES)
    return template, tlen, rs, ref_len, bw


@pytest.mark.parametrize("seed", [5, 17])
def test_codon_fill_matches_host(seed):
    """Forward/backward bands, final score, and move consistency vs the
    numpy engine (fp ties between predecessors may break differently, so
    moves are checked by predecessor-achieves-value, not bitwise)."""
    rng = np.random.default_rng(seed)
    template, tlen, rs, ref_len, bw = _pair(rng, 60)
    assert rs.do_codon_moves

    A_h, mv_h = align_np.forward_moves_vec(template, rs)
    B_h = align_np.backward_vec(template, rs)

    rt = acj.make_ref_tables(rs)
    K = acj.band_height_codon(ref_len, tlen, bw)
    Tmax, T1p = tlen + 8, tlen + 9
    tpl = np.zeros(Tmax, np.int8)
    tpl[:tlen] = template
    fwd = acj.forward_codon(jnp.asarray(tpl), tlen, rt, K, T1p,
                            want_moves=True)
    bwd = acj.backward_codon(jnp.asarray(tpl), tlen, rt, K, T1p)

    bands = np.asarray(fwd.bands)
    starts = np.asarray(fwd.starts)
    mvs = np.asarray(fwd.moves)
    bbands = np.asarray(bwd.bands)
    bstarts = np.asarray(bwd.starts)
    for j in range(tlen + 1):
        lo, hi = A_h.row_range(j)
        for i in range(lo, hi + 1):
            want = A_h[i, j]
            got = bands[j, i - starts[j]]
            if np.isfinite(want):
                assert np.isclose(got, want, rtol=1e-9, atol=1e-9), (i, j)
            else:
                assert not np.isfinite(got) or got < -1e30
            bw_ = B_h[i, j]
            bg = bbands[j, i - bstarts[j]]
            if np.isfinite(bw_):
                assert np.isclose(bg, bw_, rtol=1e-9, atol=1e-9), (i, j)
            # move consistency
            gm = mvs[j, i - starts[j]]
            if np.isfinite(want) and not (i == 0 and j == 0):
                if gm == align_np.TRACE_MATCH:
                    e = (rs.match_scores[i - 1]
                         if rs.seq[i - 1] == template[j - 1]
                         else rs.mismatch_scores[i - 1])
                    pred = A_h[i - 1, j - 1] + e
                elif gm == align_np.TRACE_INSERT:
                    pred = A_h[i - 1, j] + rs.ins_scores[i - 1]
                elif gm == align_np.TRACE_DELETE:
                    pred = A_h[i, j - 1] + rs.del_scores[i]
                elif gm == align_np.TRACE_CODON_INSERT:
                    pred = A_h[i - 3, j] + rs.codon_ins_scores[i - 3]
                elif gm == align_np.TRACE_CODON_DELETE:
                    pred = A_h[i, j - 3] + rs.codon_del_scores[i]
                else:
                    pred = np.nan
                assert np.isclose(pred, want, rtol=1e-6, atol=1e-6), (i, j, gm)
    assert np.isclose(float(np.asarray(fwd.score)), float(A_h[ref_len, tlen]),
                      rtol=1e-9)


def test_codon_proposal_scores_match_host():
    """Every single-base edit scored by the vmapped device scorer equals
    scoring_np.score_proposal (the model.jl:302-383 oracle), including
    the just_a tail and suffix-deletion edge cases."""
    rng = np.random.default_rng(9)
    template, tlen, rs, ref_len, bw = _pair(rng, 50)
    A_h, _ = align_np.forward_moves_vec(template, rs)
    B_h = align_np.backward_vec(template, rs)

    rt = acj.make_ref_tables(rs)
    K = acj.band_height_codon(ref_len, tlen, bw)
    Tmax, T1p = tlen + 8, tlen + 9
    tpl = np.zeros(Tmax, np.int8)
    tpl[:tlen] = template
    fwd = acj.forward_codon(jnp.asarray(tpl), tlen, rt, K, T1p)
    bwd = acj.backward_codon(jnp.asarray(tpl), tlen, rt, K, T1p)

    props = []
    for pos in range(tlen):
        props.append(Deletion(pos))
        props.append(Substitution(pos, int(rng.integers(0, 4))))
        props.append(Insertion(pos, int(rng.integers(0, 4))))
    props.append(Insertion(tlen, 2))
    kinds = np.array([{Substitution: 0, Deletion: 1, Insertion: 2}[type(p)]
                      for p in props], np.int32)
    poss = np.array([p.pos for p in props], np.int32)
    bases = np.array([getattr(p, "base", 0) for p in props], np.int32)
    t_cols = np.zeros(T1p, np.int8)
    t_cols[1 : tlen + 1] = template
    got = np.asarray(acj._score_proposals_codon(
        jnp.asarray(kinds), jnp.asarray(poss), jnp.asarray(bases),
        jnp.asarray(t_cols), jnp.int32(tlen),
        fwd.bands, fwd.starts, bwd.bands, bwd.starts,
        tuple(rt[:9]), K, T1p, ref_len + 1, rt.do_cins, rt.do_cdel,
    ))
    want = np.array([score_proposal(p, A_h, B_h, template, rs)
                     for p in props])
    finite = np.isfinite(want)
    np.testing.assert_allclose(got[finite], want[finite], rtol=1e-9,
                               atol=1e-9)
    assert (got[~finite] < -1e30).all()


def test_ref_aligner_device_matches_host_engine():
    """RefAligner's device routing (long refs) reproduces the host
    engine: same score, same proposal scores, same adapted bandwidth."""
    from rifraf_tpu.ops.align_codon_jax import DEVICE_THRESHOLD

    L = DEVICE_THRESHOLD + 90
    rng = np.random.default_rng(3)
    ref_len = L // 3 * 3
    ref_seq = rng.integers(0, 4, size=ref_len).astype(np.int8)
    cons = ref_seq.copy().tolist()
    for p in sorted(rng.choice(ref_len - 10, 3, replace=False))[::-1]:
        cons.insert(int(p), int(rng.integers(0, 4)))
    cons = np.array(cons, np.int8)

    ref_d = make_read_scores(ref_seq, np.full(ref_len, np.log10(0.05)), 9,
                             REF_SCORES)
    ref_h = make_read_scores(ref_seq, np.full(ref_len, np.log10(0.05)), 9,
                             REF_SCORES)

    ra_d = RefAligner()
    ra_d.realign(cons, ref_d, 0.1)
    assert ra_d._dev is not None  # long pair took the device engine

    # host engine, forced
    ra_h = RefAligner()
    max_bw = min(ref_h.bandwidth << 5, len(cons), len(ref_h))
    n_errors = old = np.iinfo(np.int64).max
    while True:
        ra_h.A, ra_h.Amoves = align_np.forward_moves_vec(cons, ref_h)
        if ref_h.bandwidth >= max_bw:
            break
        old, n_errors = n_errors, align_np.count_errors_in_moves(
            ra_h.Amoves, cons, ref_h.seq)
        from rifraf_tpu.utils.mathops import poisson_cquantile

        if n_errors > poisson_cquantile(ref_h.est_n_errors, 0.1) and \
                n_errors < old:
            ref_h.bandwidth = min(ref_h.bandwidth * 2, max_bw)
        else:
            break
    ra_h.B = align_np.backward_vec(cons, ref_h)

    assert ref_d.bandwidth == ref_h.bandwidth
    score_h = float(ra_h.A[ra_h.A.nrows - 1, ra_h.A.ncols - 1])
    assert np.isclose(ra_d.score(), score_h, rtol=1e-9)

    props = [Deletion(5), Substitution(40, 1), Insertion(100, 2),
             Deletion(len(cons) - 1), Insertion(len(cons), 3)]
    got = ra_d.score_proposals(props, cons, ref_d)
    newcols = np.full((ra_h.A.nrows, 4), -np.inf)
    want = np.array([
        score_proposal(p, ra_h.A, ra_h.B, cons, ref_h, newcols)
        for p in props
    ])
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)


def test_align_moves_routed_equivalence():
    """generate's routed align_moves produces a path with the same
    alignment score as the host engine's (tie-broken paths may differ;
    their scores may not)."""
    from rifraf_tpu.engine.generate import _align_moves_routed
    from rifraf_tpu.ops.align_codon_jax import DEVICE_THRESHOLD

    rng = np.random.default_rng(11)
    ref_len = (DEVICE_THRESHOLD + 60) // 3 * 3
    ref_seq = rng.integers(0, 4, size=ref_len).astype(np.int8)
    cons = ref_seq.copy().tolist()
    cons.insert(200, 2)
    cons = np.array(cons, np.int8)
    rs = make_read_scores(ref_seq, np.full(ref_len, np.log10(0.05)), 12,
                          REF_SCORES)
    moves_d = _align_moves_routed(cons, rs, skew_matches=True)
    moves_h = align_np.align_moves(cons, rs, skew_matches=True)

    def path_score(moves):
        i = j = 0
        total = 0.0
        for m in moves:
            if m == align_np.TRACE_MATCH:
                i += 1
                j += 1
                total += (rs.match_scores[i - 1]
                          if rs.seq[i - 1] == cons[j - 1]
                          else rs.mismatch_scores[i - 1] * 0.99)
            elif m == align_np.TRACE_INSERT:
                i += 1
                total += rs.ins_scores[i - 1]
            elif m == align_np.TRACE_DELETE:
                j += 1
                total += rs.del_scores[i]
            elif m == align_np.TRACE_CODON_INSERT:
                i += 3
                total += rs.codon_ins_scores[i - 3]
            else:
                j += 3
                total += rs.codon_del_scores[i]
        assert i == len(rs.seq) and j == len(cons)
        return total

    assert np.isclose(path_score(moves_d), path_score(moves_h), rtol=1e-9)
