"""Lane packing as an execution strategy: packed multi-cluster launches
must be bit-identical to per-problem runs, and the flush/accounting
surfaces must report the packing honestly.

Two layers:

- Fast (host-only): the serve micro-batcher's lane-capacity flush
  (``pending * Npad >= lane_target``), the ServerStats lane-occupancy
  rollup, and the executed-lane accounting on SweepStats/BucketStats.
- Slow (whole-sweep compiles): sweeps with the lane-packing floor
  (``lane_target=128`` packs many small clusters into each launch) vs
  one-cluster-per-launch sweeps (``lane_target=0, cluster_chunk=1``),
  across mixed band geometries (different bandwidths and lengths) and
  both ``do_alignment_proposals`` settings. Packing changes WHICH
  launch a cluster rides in, never its result: pad clusters carry
  weight 0 everywhere and band-height padding is masked by the band
  geometry (the sweep module's core invariant).
"""

from types import SimpleNamespace

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = pytest.importorskip("jax.numpy")

from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.parallel.sweep_sharded import (
    SEG_TMAX_MAX,
    BucketPlan,
    SegmentBucketPlan,
    _ClusterInfo,
    _lane_slots,
    plan_sweep,
    sweep_clusters_sharded,
)
from rifraf_tpu.serve.batcher import MicroBatcher, segment_eligible
from rifraf_tpu.serve.request import Request, ServeConfig
from rifraf_tpu.serve.stats import ServerStats
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p
from rifraf_tpu.utils.shapes import pack_segments

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _mixed_clusters(seed=0):
    """Small clusters spanning several band geometries: bandwidths 4/9/
    30 and lengths 45-75 produce distinct (Lpad, K0) signatures and
    entry band heights."""
    rng = np.random.default_rng(seed)
    from rifraf_tpu.engine.params import RifrafParams

    scores = RifrafParams().scores
    out = []
    for nseqs, length, bw in [(4, 50, 4), (5, 60, 9), (3, 45, 30),
                              (6, 75, 9), (4, 52, 4), (5, 48, 4)]:
        _, _, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=nseqs, length=length, error_rate=0.03, rng=rng,
            seq_errors=SEQ_ERRORS,
        )
        out.append([
            make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                             bw, scores)
            for s, p in zip(seqs, phreds)
        ])
    return out


# ------------------------------------------------------ fast: host logic


def _req(rid, key):
    return Request(id=rid, cluster=[], info=None, key=key, t_submit=0.0,
                   deadline=None)


def _sreq(rid, key, n_reads):
    """A request that carries its read count (the segment packer's
    input); the batcher only touches ``info.n_reads``."""
    return Request(id=rid, cluster=[], info=SimpleNamespace(n_reads=n_reads),
                   key=key, t_submit=0.0, deadline=None)


def _info(n_reads, max_len=50, tlen0=48, entry_k=16):
    return _ClusterInfo(n_reads=n_reads, max_len=max_len, seed_idx=0,
                        tlen0=tlen0, entry_k=entry_k,
                        useful=n_reads * max_len)


# ---------------------------------------- fast: segment packer properties


def test_seg_tmax_matches_dense_block_threshold():
    """The packer's template ceiling must track the unblocked dense
    sweep's: fused_step_segmented declines blocked-dense templates
    (whose internal reductions are not segment-aware), so admitting a
    longer template here would fail at trace time instead of routing
    whole-block."""
    from rifraf_tpu.ops.fused import DENSE_BLOCK_THRESHOLD

    assert SEG_TMAX_MAX == DENSE_BLOCK_THRESHOLD


def test_pack_segments_first_fit_properties():
    counts = [5, 11, 3, 120, 7]
    pk = pack_segments(counts, lanes=128)
    # every problem lands exactly once, with its true read count
    placed = sorted(
        (i, n) for blk in pk.blocks for i, _, n in blk
    )
    assert placed == sorted(enumerate(counts))
    assert pk.npad <= 128 and pk.n_seg == max(len(b) for b in pk.blocks)
    assert pk.occupancy == pytest.approx(
        sum(counts) / (len(pk.blocks) * pk.npad)
    )
    for b, blk in enumerate(pk.blocks):
        # input order within a block, contiguous offsets, and a seg-id
        # mask that tags exactly each member's lanes with its slot
        assert [i for i, _, _ in blk] == sorted(i for i, _, _ in blk)
        off = 0
        for s, (i, o, n) in enumerate(blk):
            assert o == off
            assert pk.seg_ids[b][o : o + n] == [s] * n
            off += n
        assert off <= 128
        assert pk.seg_ids[b][off:] == [0] * (pk.npad - off)


def test_pack_segments_single_block_npad():
    """One block packs tight: npad is the used width (align grid), not
    a full lane tile."""
    pk = pack_segments([5, 3], lanes=128)
    assert len(pk.blocks) == 1 and pk.npad == 8
    assert pk.seg_ids[0] == [0] * 5 + [1] * 3
    assert pk.blocks[0] == [(0, 0, 5), (1, 5, 3)]


def test_pack_segments_align():
    """align rounds each problem's lane footprint; gap lanes between a
    member's reads and the next offset keep the member's slot id."""
    pk = pack_segments([5, 3], lanes=128, align=8)
    assert len(pk.blocks) == 1 and pk.npad == 16
    assert pk.blocks[0] == [(0, 0, 5), (1, 8, 3)]
    assert pk.seg_ids[0] == [0] * 8 + [1] * 8


def test_pack_segments_declines():
    with pytest.raises(ValueError):
        pack_segments([129], lanes=128)  # wider than one block
    with pytest.raises(ValueError):
        pack_segments([4, 0])  # empty problem


# --------------------------- fast: segment-masked reduction bit-identity


def test_segment_reduce_masking_is_exact():
    """The structural property packed bit-identity rests on: a
    per-segment masked sum equals (bit for bit) the SAME-width
    reduction with foreign lanes zero-weighted — masking happens before
    multiplying, zeros are exact — and is therefore completely
    independent of foreign-lane content, NaN/-inf included. (Packed vs
    PER-PROBLEM bit-identity is asserted end-to-end by the slow sweep/
    serve suites at the pipeline's real reduction shapes; a bare
    narrower reduce may round differently, which is why the executed
    paths compare like-for-like.) Mixed magnitudes spanning 16 orders
    stress float associativity."""
    from rifraf_tpu.ops.fused import (
        masked_weighted_sum,
        segment_masked_sum,
        segment_masked_sum_lanes,
        segment_weights,
    )

    rng = np.random.default_rng(0)
    counts = [5, 11, 3]
    npad = 24  # 19 real lanes + 5 pad lanes (seg id 0, weight 0)
    seg_ids = np.zeros(npad, np.int32)
    w = np.zeros(npad, np.float32)
    off = 0
    for s, n in enumerate(counts):
        seg_ids[off : off + n] = s
        w[off : off + n] = rng.uniform(0.5, 2.0, n).astype(np.float32)
        off += n
    # magnitudes spanning 16 orders stress float associativity
    x = (rng.uniform(-1.0, 1.0, (7, npad))
         * 10.0 ** rng.integers(-8, 9, (7, npad))).astype(np.float32)

    seg_w = segment_weights(jnp.asarray(seg_ids), jnp.asarray(w), 3)
    got_reads = np.asarray(segment_masked_sum(seg_w, jnp.asarray(x.T)))
    got_lanes = np.asarray(segment_masked_sum_lanes(seg_w, jnp.asarray(x)))
    for s in range(3):
        wz = jnp.asarray(np.where(seg_ids == s, w, 0.0).astype(np.float32))
        want = np.asarray(masked_weighted_sum(wz, jnp.asarray(x.T)))
        np.testing.assert_array_equal(got_reads[s], want)
        # the lane-LAST variant matches the same-orientation reduce
        # (axis order changes the lowering, so each epilogue compares
        # against its own orientation)
        want_l = np.asarray(jnp.sum(
            jnp.where(wz > 0, jnp.asarray(x), np.float32(0.0)) * wz,
            axis=-1,
        ))
        np.testing.assert_array_equal(got_lanes[s], want_l)

    # foreign-lane independence: poison every lane OUTSIDE segment 1
    # with NaN/-inf/huge garbage — segment 1's results must not move a
    # bit (zero-weight lanes are masked BEFORE the multiply)
    x_poison = x.copy()
    x_poison[:, seg_ids != 1] = np.float32(np.nan)
    x_poison[0, 0] = np.float32(-np.inf)
    x_poison[1, 20] = np.float32(1e38)
    got_p = np.asarray(
        segment_masked_sum(seg_w, jnp.asarray(x_poison.T))
    )
    np.testing.assert_array_equal(got_p[1], got_reads[1])


def test_segment_union_pad_lanes_are_noops():
    """Pad/gap lanes duplicate a read of their assigned slot, so the
    per-segment edits union (which has no weight mask) is unchanged by
    them — and foreign lanes never leak into a segment's union."""
    from rifraf_tpu.ops.fused import segment_union_max_lanes

    seg_ids = jnp.asarray([0, 0, 1, 1, 1, 0, 0, 0], jnp.int32)
    x = np.zeros((4, 8), np.float32)
    x[:, 0] = [1, 0, 1, 0]  # segment 0's real reads
    x[:, 1] = [0, 1, 0, 0]
    x[:, 2:5] = np.array([[0, 0, 0, 1]]).T  # segment 1
    x[:, 5:] = x[:, :1]  # pad lanes: duplicates of seg-0 read 0
    um = np.asarray(segment_union_max_lanes(seg_ids, jnp.asarray(x), 2))
    np.testing.assert_array_equal(um[0], [1, 1, 1, 0])
    np.testing.assert_array_equal(um[1], [0, 0, 0, 1])


# ----------------------------- fast: batcher read-granularity grouping


def test_batcher_segment_group_flushes_on_reads():
    """Segment-packed buckets flush on pending READS, not pending
    blocks: 25 five-read requests occupy 125 lanes of a shared block
    (< 128), where whole-Npad counting (8 lanes each) would have
    over-flushed at 16."""
    b = MicroBatcher(ServeConfig(max_batch=64, lane_target=128))
    k8 = (8, 64, 64, 16)
    for i in range(25):
        assert b.add(_sreq(f"r{i}", k8, 5)) is None
    full = b.add(_sreq("r25", k8, 5))  # 130 reads >= 128
    assert full is not None and len(full) == 26


def test_batcher_segment_groups_merge_npad_buckets():
    """Segment grouping keys on the SHAPE axes only: requests whose
    Npad differs (5 vs 11 reads) share one pending bucket and pack into
    the same lane blocks."""
    b = MicroBatcher(ServeConfig(max_batch=64, lane_target=128))
    shape = (64, 64, 16)
    for i in range(7):
        assert b.add(_sreq(f"a{i}", (8,) + shape, 5)) is None
        assert b.add(_sreq(f"b{i}", (16,) + shape, 11)) is None
    assert b.add(_sreq("a7", (8,) + shape, 5)) is None  # 117 reads
    assert b.depth() == 15  # ONE merged bucket across both Npad keys
    full = b.add(_sreq("b7", (16,) + shape, 11))  # 128 reads: flush
    assert full is not None and len(full) == 16


def test_batcher_segment_ineligible_routes_whole_block():
    """Requests the packer declines (Npad fills a tile alone, or a
    blocked-dense template) group under the whole-block key."""
    assert not segment_eligible((128, 256, 256, 32), 128)
    assert not segment_eligible((8, 64, SEG_TMAX_MAX + 64, 16), 128)
    assert segment_eligible((8, 64, 64, 16), 128)
    b = MicroBatcher(ServeConfig(max_batch=64, lane_target=128))
    # a lone full-tile request flushes immediately on lane capacity
    assert b.add(_sreq("big", (128, 256, 256, 32), 100)) is not None


def test_batcher_segment_pack_config_off():
    """segment_pack=False restores whole-block grouping: 16 Npad=8
    requests fill 128 lanes of whole blocks regardless of read counts."""
    b = MicroBatcher(ServeConfig(max_batch=64, lane_target=128,
                                 segment_pack=False))
    k8 = (8, 64, 64, 16)
    for i in range(15):
        assert b.add(_sreq(f"r{i}", k8, 5)) is None
    assert b.add(_sreq("r15", k8, 5)) is not None  # 16 * 8 == 128


# ------------------------------------ fast: planner segment-group rules


def test_plan_sweep_segments_small_clusters():
    """Small same-shape clusters plan as ONE segment-packed bucket (a
    5-read and an 11-read cluster share 16 lanes instead of 8+16);
    tile-filling clusters stay on the whole-block path."""
    infos = [_info(5), _info(11), _info(3), _info(128)]
    plans = plan_sweep([], infos=infos, lane_target=128,
                       segment_pack=True)
    segs = [p for p in plans if isinstance(p, SegmentBucketPlan)]
    blks = [p for p in plans if isinstance(p, BucketPlan)]
    assert len(segs) == 1 and len(blks) == 1
    assert blks[0].chunks == [[3]]  # the 128-read cluster
    (seg,) = segs
    assert seg.key[0] == 24  # 19 lanes -> read grid 8
    assert seg.sp == 3 and len(seg.chunks) == 1
    (packs,) = seg.chunks
    assert sorted(i for pk in packs for i, _, _ in pk.members) == [0, 1, 2]


def test_plan_sweep_segment_mesh_decline():
    """A mesh larger than the pack count would serialize the (sharded)
    pack axis, so the planner declines packing and shards one cluster
    per device instead; a mesh the packs can fill stays packed."""
    small = [_info(8) for _ in range(8)]
    # 8 clusters x 8 reads -> one 64-lane pack: packed on 1 device,
    # declined (cluster-per-slot whole block) on an 8-device mesh
    (p1,) = plan_sweep([], infos=small, lane_target=128,
                       segment_pack=True, n_axis=1)
    assert isinstance(p1, SegmentBucketPlan)
    (p8,) = plan_sweep([], infos=small, lane_target=128,
                       segment_pack=True, n_axis=8)
    assert isinstance(p8, BucketPlan)
    assert p8.gp == 8 and len(p8.chunks) == 1
    # 16 x 60-read clusters pack two per block -> 8 packs fill the
    # 8-device mesh: packing survives
    wide = [_info(60, max_len=60) for _ in range(16)]
    (pw,) = plan_sweep([], infos=wide, lane_target=128,
                       segment_pack=True, n_axis=8)
    assert isinstance(pw, SegmentBucketPlan)
    assert sum(len(c) for c in pw.chunks) == 8


def test_plan_sweep_segment_env_opt_out(monkeypatch):
    infos = [_info(5), _info(11), _info(3)]
    monkeypatch.setenv("RIFRAF_TPU_SEGMENT_PACK", "0")
    plans = plan_sweep([], infos=infos, lane_target=128)
    assert all(isinstance(p, BucketPlan) for p in plans)
    # the explicit argument overrides the env gate
    plans = plan_sweep([], infos=infos, lane_target=128,
                       segment_pack=True)
    assert any(isinstance(p, SegmentBucketPlan) for p in plans)


def test_mega_declines_segment_packed_launch():
    """The megakernel fills one template per launch; multi-segment
    packed blocks must route to the XLA segmented step."""
    from rifraf_tpu.ops import fused_pallas

    ok, reason = fused_pallas.mega_segment_eligible(1)
    assert ok
    ok, reason = fused_pallas.mega_segment_eligible(2)
    assert not ok and "segment" in reason


def test_batcher_lane_capacity_flush():
    """A big-cluster bucket (Npad=64) flushes at 2 pending requests
    (2 * 64 >= 128) instead of waiting for max_batch=16."""
    b = MicroBatcher(ServeConfig(max_batch=16, lane_target=128))
    k64 = (64, 128, 128, 32)
    assert b.add(_req("a", k64)) is None
    full = b.add(_req("b", k64))
    assert full is not None and [r.id for r in full] == ["a", "b"]
    assert b.depth() == 0


def test_batcher_lane_flush_small_clusters_wait():
    """Small clusters (Npad=8) underfill the lane axis, so the count
    flush (max_batch) still governs: 15 pending at 8 lanes each stay
    pending until the 16th arrives."""
    b = MicroBatcher(ServeConfig(max_batch=16, lane_target=128))
    k8 = (8, 64, 64, 16)
    for i in range(15):
        assert b.add(_req(f"r{i}", k8)) is None
    assert b.add(_req("r15", k8)) is not None  # 16 * 8 == 128: both fire


def test_batcher_lane_flush_disabled():
    b = MicroBatcher(ServeConfig(max_batch=16, lane_target=0))
    k64 = (64, 128, 128, 32)
    for i in range(15):
        assert b.add(_req(f"r{i}", k64)) is None


def test_server_stats_lane_occupancy():
    s = ServerStats()
    s.note_batch(n_real=2, gp=2, useful_cells=100, padded_cells=200,
                 useful_lanes=100, lane_slots=128, cluster_lanes=128)
    s.note_batch(n_real=3, gp=4, useful_cells=100, padded_cells=400,
                 useful_lanes=28, lane_slots=128, cluster_lanes=48)
    snap = s.snapshot()
    assert snap["lane_occupancy"] == pytest.approx(176 / 256)
    assert snap["lane_occupancy_reads"] == pytest.approx(128 / 256)
    s.note_model_bytes(2.5e9)
    assert s.snapshot()["model_gb"] == pytest.approx(2.5)


def test_lane_slots_rounding():
    assert _lane_slots(16, 8) == 128
    assert _lane_slots(1, 8) == 128  # a quarter-full tile still costs one
    assert _lane_slots(2, 120) == 256  # 240 lanes -> two tiles
    assert _lane_slots(17, 8) == 256


# ------------------------------------- slow: packed vs per-problem sweeps


@pytest.mark.slow
@pytest.mark.parametrize("proposals", [False, True])
def test_packed_sweep_matches_per_problem(proposals):
    """The tentpole property: packing multiple small clusters into the
    128-lane axis of one launch (lane_target=128 overriding
    cluster_chunk=1) is bit-identical — consensus, score, iteration
    count, convergence — to dispatching every cluster in its own launch
    (lane_target=0, cluster_chunk=1), across mixed band geometries and
    both candidate-proposal modes."""
    clusters = _mixed_clusters(seed=3)
    packed, pstats = sweep_clusters_sharded(
        clusters, cluster_chunk=1, lane_target=128,
        do_alignment_proposals=proposals, return_stats=True,
    )
    solo, sstats = sweep_clusters_sharded(
        clusters, cluster_chunk=1, lane_target=0,
        do_alignment_proposals=proposals, return_stats=True,
    )
    for g, (a, b) in enumerate(zip(packed, solo)):
        assert np.array_equal(a.consensus, b.consensus), g
        assert a.score == b.score, g
        assert a.n_iters == b.n_iters, g
        assert a.converged == b.converged, g
    # packing is real: fewer launches, better read-granularity lane
    # fill. (Block-granularity lane_occupancy is NOT comparable across
    # the two runs once segment packing reserves lanes per read instead
    # of per whole Npad block — the packed numerator shrinks to the
    # read count while the solo one keeps counting reserved blocks.)
    assert pstats.n_chunks < sstats.n_chunks
    assert pstats.lane_occupancy_reads > sstats.lane_occupancy_reads
    # reservation can only be at least as coarse as the reads it holds
    assert pstats.lane_occupancy >= pstats.lane_occupancy_reads
    for bs in pstats.buckets:
        assert bs.lane_slots == bs.n_chunks * _lane_slots(bs.gp, bs.key[0])
        assert 0.0 < bs.lane_slot_occupancy <= 1.0


# -------------------- slow: segmented fused step vs per-problem oracle


@pytest.mark.slow
@pytest.mark.parametrize("want_stats", [False, True])
def test_fused_step_segmented_matches_per_problem(want_stats):
    """Kernel-level identity: three problems with distinct band
    geometries (bandwidths 4/9/30, different template lengths) packed
    into one lane block through ``fused_step_segmented`` produce the
    SAME bits — per-segment totals, per-lane scores, dense tables, and
    (stats on) traceback error counts + edits unions — as three
    independent ``fused_step_full`` launches at the same (K, Tmax)."""
    from rifraf_tpu.ops import align_jax
    from rifraf_tpu.ops.fused import (
        fused_step_full,
        fused_step_segmented,
        pack_layout,
    )

    clusters = _mixed_clusters(seed=5)[:3]
    counts = [len(c) for c in clusters]
    tlens = [len(c[0]) for c in clusters]
    Tmax = max(tlens) + 8
    tmpl = np.zeros((3, Tmax), np.int8)
    for s, c in enumerate(clusters):
        tmpl[s, : tlens[s]] = c[0].seq
    L = max(len(r) for c in clusters for r in c) + 4

    npad = 16  # 12 real lanes + tail pads (seg id 0, weight 0)
    reads, seg_ids, bws = [], [], []
    for s, c in enumerate(clusters):
        reads.extend(c)
        seg_ids.extend([s] * len(c))
        bws.extend(r.bandwidth for r in c)
    pad = npad - len(reads)
    reads += [clusters[0][0]] * pad  # duplicates of slot 0's first read
    seg_ids += [0] * pad
    bws += [clusters[0][0].bandwidth] * pad
    weights = np.asarray([1.0] * (npad - pad) + [0.0] * pad, np.float32)
    b = batch_reads(reads, max_len=L, dtype=np.float32)
    lane_tlens = np.asarray(tlens, np.int32)[np.asarray(seg_ids)]
    geom_all = align_jax.BandGeometry.make(
        jnp.asarray(b.lengths), jnp.asarray(lane_tlens),
        jnp.asarray(bws, np.int32),
    )
    K = int(np.asarray(geom_all.nd).max() + np.asarray(geom_all.offset).max())
    K = ((K + 7) // 8) * 8

    seg = fused_step_segmented(
        jnp.asarray(tmpl), jnp.asarray(tlens, np.int32),
        jnp.asarray(seg_ids, np.int32), jnp.asarray(b.seq),
        jnp.asarray(b.match), jnp.asarray(b.mismatch), jnp.asarray(b.ins),
        jnp.asarray(b.dels), jnp.asarray(b.lengths),
        jnp.asarray(bws, np.int32), jnp.asarray(weights), K, 3,
        want_stats=want_stats,
    )

    T1 = Tmax + 1
    off = 0
    for s, c in enumerate(clusters):
        n, tlen = counts[s], tlens[s]
        bi = batch_reads(list(c), max_len=L, dtype=np.float32)
        bw_i = jnp.asarray([r.bandwidth for r in c], np.int32)
        geom = align_jax.BandGeometry.make(
            jnp.asarray(bi.lengths), jnp.full((n,), tlen, jnp.int32), bw_i
        )
        _, _, _, packed = fused_step_full(
            jnp.asarray(tmpl[s]), jnp.asarray(bi.seq),
            jnp.asarray(bi.match), jnp.asarray(bi.mismatch),
            jnp.asarray(bi.ins), jnp.asarray(bi.dels), geom,
            jnp.ones((n,), jnp.float32), K, want_stats=want_stats,
        )
        packed = np.asarray(packed)
        lay = pack_layout(n, T1, want_stats)
        np.testing.assert_array_equal(
            np.asarray(seg["total"])[s], packed[slice(*lay["total"])][0],
            err_msg=f"total s={s}",
        )
        np.testing.assert_array_equal(
            np.asarray(seg["scores"])[off : off + n],
            packed[slice(*lay["scores"])], err_msg=f"scores s={s}",
        )
        for name, hi, shp in (("sub", tlen, (T1, 4)),
                              ("ins", tlen + 1, (T1, 4)),
                              ("del", tlen, (T1,))):
            want = packed[slice(*lay[name])].reshape(shp)[:hi]
            np.testing.assert_array_equal(
                np.asarray(seg[name])[s][:hi], want, err_msg=f"{name} s={s}"
            )
        if want_stats:
            np.testing.assert_array_equal(
                np.asarray(seg["n_errors"])[off : off + n],
                packed[slice(*lay["n_errors"])], err_msg=f"n_errors s={s}",
            )
            np.testing.assert_array_equal(
                np.asarray(seg["edits"])[s][: tlen + 1],
                packed[slice(*lay["edits"])].reshape(T1, 9)[: tlen + 1],
                err_msg=f"edits s={s}",
            )
        off += n


@pytest.mark.slow
def test_stats_panel_layouts_bit_identical(monkeypatch):
    """The two stats panel layouts — the int8 move-band Pallas panel
    sweep (``int8_moves_ok``) and the int32/XLA moves-band scan the env
    opt-out pins — must produce bit-identical traceback error counts
    and edits unions on the same panel-fused inputs, so segment-packed
    accounting stays layout-independent."""
    from rifraf_tpu.models.errormodel import ErrorModel, Scores
    from rifraf_tpu.ops import align_jax, dense_pallas, fill_pallas
    from rifraf_tpu.ops import stats_pallas

    scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))
    rng = np.random.default_rng(17)
    tlen = 40
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(3):
        slen = int(rng.integers(tlen - 5, tlen + 6))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, 4, scores))
    batch = batch_reads(reads, dtype=np.float32)
    geom = align_jax.batch_geometry(batch, tlen)
    K = fill_pallas.uniform_band_height(
        np.asarray(geom.offset), np.asarray(geom.nd)
    )
    C = 8
    assert stats_pallas.int8_moves_ok(K, C)  # uniform K is 8-aligned
    Tmax = ((tlen + 63) // 64) * 64
    T1p = Tmax + 64
    tpl = np.zeros(Tmax, np.int8)
    tpl[:tlen] = template
    Npad = ((batch.n_reads + 127) // 128) * 128
    bufs = fill_pallas.build_fill_buffers(
        jnp.asarray(batch.seq), jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
        jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
    )
    args = (jnp.asarray(tpl), jnp.int32(tlen), bufs, geom,
            jnp.ones(batch.n_reads, np.float32), K, T1p, C)

    int8_out = dense_pallas.fused_tables_pallas_panels(
        *args, panel_cols=16, want_stats=True, interpret=True,
    )
    monkeypatch.setenv("RIFRAF_TPU_STATS_IMPL", "xla")
    assert not stats_pallas.use_pallas_stats()
    xla_out = dense_pallas.fused_tables_pallas_panels(
        *args, panel_cols=16, want_stats=True, interpret=True,
    )
    N = batch.n_reads
    np.testing.assert_array_equal(
        np.asarray(int8_out["n_errors"])[:N],
        np.asarray(xla_out["n_errors"])[:N],
    )
    np.testing.assert_array_equal(
        np.asarray(int8_out["edits"])[: tlen + 1],
        np.asarray(xla_out["edits"])[: tlen + 1],
    )
