"""Lane packing as an execution strategy: packed multi-cluster launches
must be bit-identical to per-problem runs, and the flush/accounting
surfaces must report the packing honestly.

Two layers:

- Fast (host-only): the serve micro-batcher's lane-capacity flush
  (``pending * Npad >= lane_target``), the ServerStats lane-occupancy
  rollup, and the executed-lane accounting on SweepStats/BucketStats.
- Slow (whole-sweep compiles): sweeps with the lane-packing floor
  (``lane_target=128`` packs many small clusters into each launch) vs
  one-cluster-per-launch sweeps (``lane_target=0, cluster_chunk=1``),
  across mixed band geometries (different bandwidths and lengths) and
  both ``do_alignment_proposals`` settings. Packing changes WHICH
  launch a cluster rides in, never its result: pad clusters carry
  weight 0 everywhere and band-height padding is masked by the band
  geometry (the sweep module's core invariant).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.parallel.sweep_sharded import (
    _lane_slots,
    sweep_clusters_sharded,
)
from rifraf_tpu.serve.batcher import MicroBatcher
from rifraf_tpu.serve.request import Request, ServeConfig
from rifraf_tpu.serve.stats import ServerStats
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _mixed_clusters(seed=0):
    """Small clusters spanning several band geometries: bandwidths 4/9/
    30 and lengths 45-75 produce distinct (Lpad, K0) signatures and
    entry band heights."""
    rng = np.random.default_rng(seed)
    from rifraf_tpu.engine.params import RifrafParams

    scores = RifrafParams().scores
    out = []
    for nseqs, length, bw in [(4, 50, 4), (5, 60, 9), (3, 45, 30),
                              (6, 75, 9), (4, 52, 4), (5, 48, 4)]:
        _, _, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=nseqs, length=length, error_rate=0.03, rng=rng,
            seq_errors=SEQ_ERRORS,
        )
        out.append([
            make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                             bw, scores)
            for s, p in zip(seqs, phreds)
        ])
    return out


# ------------------------------------------------------ fast: host logic


def _req(rid, key):
    return Request(id=rid, cluster=[], info=None, key=key, t_submit=0.0,
                   deadline=None)


def test_batcher_lane_capacity_flush():
    """A big-cluster bucket (Npad=64) flushes at 2 pending requests
    (2 * 64 >= 128) instead of waiting for max_batch=16."""
    b = MicroBatcher(ServeConfig(max_batch=16, lane_target=128))
    k64 = (64, 128, 128, 32)
    assert b.add(_req("a", k64)) is None
    full = b.add(_req("b", k64))
    assert full is not None and [r.id for r in full] == ["a", "b"]
    assert b.depth() == 0


def test_batcher_lane_flush_small_clusters_wait():
    """Small clusters (Npad=8) underfill the lane axis, so the count
    flush (max_batch) still governs: 15 pending at 8 lanes each stay
    pending until the 16th arrives."""
    b = MicroBatcher(ServeConfig(max_batch=16, lane_target=128))
    k8 = (8, 64, 64, 16)
    for i in range(15):
        assert b.add(_req(f"r{i}", k8)) is None
    assert b.add(_req("r15", k8)) is not None  # 16 * 8 == 128: both fire


def test_batcher_lane_flush_disabled():
    b = MicroBatcher(ServeConfig(max_batch=16, lane_target=0))
    k64 = (64, 128, 128, 32)
    for i in range(15):
        assert b.add(_req(f"r{i}", k64)) is None


def test_server_stats_lane_occupancy():
    s = ServerStats()
    s.note_batch(n_real=2, gp=2, useful_cells=100, padded_cells=200,
                 useful_lanes=100, lane_slots=128, cluster_lanes=128)
    s.note_batch(n_real=3, gp=4, useful_cells=100, padded_cells=400,
                 useful_lanes=28, lane_slots=128, cluster_lanes=48)
    snap = s.snapshot()
    assert snap["lane_occupancy"] == pytest.approx(176 / 256)
    assert snap["lane_occupancy_reads"] == pytest.approx(128 / 256)
    s.note_model_bytes(2.5e9)
    assert s.snapshot()["model_gb"] == pytest.approx(2.5)


def test_lane_slots_rounding():
    assert _lane_slots(16, 8) == 128
    assert _lane_slots(1, 8) == 128  # a quarter-full tile still costs one
    assert _lane_slots(2, 120) == 256  # 240 lanes -> two tiles
    assert _lane_slots(17, 8) == 256


# ------------------------------------- slow: packed vs per-problem sweeps


@pytest.mark.slow
@pytest.mark.parametrize("proposals", [False, True])
def test_packed_sweep_matches_per_problem(proposals):
    """The tentpole property: packing multiple small clusters into the
    128-lane axis of one launch (lane_target=128 overriding
    cluster_chunk=1) is bit-identical — consensus, score, iteration
    count, convergence — to dispatching every cluster in its own launch
    (lane_target=0, cluster_chunk=1), across mixed band geometries and
    both candidate-proposal modes."""
    clusters = _mixed_clusters(seed=3)
    packed, pstats = sweep_clusters_sharded(
        clusters, cluster_chunk=1, lane_target=128,
        do_alignment_proposals=proposals, return_stats=True,
    )
    solo, sstats = sweep_clusters_sharded(
        clusters, cluster_chunk=1, lane_target=0,
        do_alignment_proposals=proposals, return_stats=True,
    )
    for g, (a, b) in enumerate(zip(packed, solo)):
        assert np.array_equal(a.consensus, b.consensus), g
        assert a.score == b.score, g
        assert a.n_iters == b.n_iters, g
        assert a.converged == b.converged, g
    # packing is real: fewer launches, better lane fill at both levels
    assert pstats.n_chunks < sstats.n_chunks
    assert pstats.lane_occupancy > sstats.lane_occupancy
    assert pstats.lane_occupancy_reads > sstats.lane_occupancy_reads
    for bs in pstats.buckets:
        assert bs.lane_slots == bs.n_chunks * _lane_slots(bs.gp, bs.key[0])
        assert 0.0 < bs.lane_slot_occupancy <= 1.0
