"""rifraf-lint self-tests: planted violations per pass (exact finding
locations), suppression semantics, the clean-tree zero-findings gate,
and regression tests for the true findings this suite surfaced when
first run (spool fingerprint integrity knobs, fingerprint helper
centralization).

Note: env-gate names and suppression markers that belong to FIXTURES
are built by string concatenation (``"RIFRAF_TPU_" + "X"``) so the real
analyzer — which scans tests/ for whole-string env-gate constants and
every parsed file for suppression comments — does not see them in THIS
file's source.
"""

import textwrap
import types

import pytest

from rifraf_tpu.analysis import PASS_IDS, run_all
from rifraf_tpu.analysis import dtypes as dtypes_pass
from rifraf_tpu.analysis import envgates as envgates_pass
from rifraf_tpu.analysis import keys as keys_pass
from rifraf_tpu.analysis import layout as layout_pass
from rifraf_tpu.analysis import races as races_pass
from rifraf_tpu.analysis.common import Project


def repo_root():
    from pathlib import Path

    import rifraf_tpu

    return Path(rifraf_tpu.__file__).resolve().parent.parent


def make_project(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(tmp_path)


# ---------------------------------------------------------------------
# pass 1: cache-key completeness
# ---------------------------------------------------------------------

FACTORY_REG = types.SimpleNamespace(
    PROGRAM_IDENTITY_KNOBS=("band_dtype", "input_enc"),
    KNOB_ALIASES={"band_dtype": ("band_dtype",),
                  "input_enc": ("input_enc",)},
    FACTORY_SCAN=("pkg/factories.py",),
    PROGRAM_FACTORIES={
        ("pkg/factories.py", "_runner"): {
            "required": ("band_dtype", "input_enc"),
            "exempt": {},
        },
    },
)

FACTORY_SRC = """\
import functools


@functools.lru_cache(maxsize=8)
def _runner(K, T1, band_dtype="f32"):
    return K


@functools.lru_cache(maxsize=8)
def _rogue(K):
    return K
"""


def test_cache_keys_missing_knob_and_unregistered(tmp_path):
    project = make_project(tmp_path, {"pkg/factories.py": FACTORY_SRC})
    found = keys_pass.check_cache_keys(project, FACTORY_REG)
    assert len(found) == 2, found
    by_line = {f.line: f.message for f in found}
    # _runner (def at line 5) carries band_dtype but not input_enc
    assert "input_enc" in by_line[5]
    # _rogue (def at line 10) is lru-cached but unregistered
    assert "not in" in by_line[10]
    assert all(f.pass_id == "cache-keys" for f in found)


def test_cache_keys_registry_self_check(tmp_path):
    reg = types.SimpleNamespace(
        PROGRAM_IDENTITY_KNOBS=("band_dtype", "input_enc", "impl"),
        KNOB_ALIASES={"band_dtype": ("band_dtype",),
                      "input_enc": ("input_enc",),
                      "impl": ("impl",)},
        FACTORY_SCAN=("pkg/factories.py",),
        PROGRAM_FACTORIES={
            ("pkg/factories.py", "_runner"): {
                # 'impl' neither required nor exempted -> self-check
                "required": ("band_dtype",),
                "exempt": {"input_enc": "fixture reason"},
            },
            ("pkg/factories.py", "_other"): {
                "required": (),
                "exempt": {"band_dtype": "r", "input_enc": "r",
                           "impl": "r"},
            },
            # registered but gone from the tree -> stale-row finding
            ("pkg/factories.py", "_gone"): {
                "required": (), "exempt": {},
            },
        },
    )
    src = FACTORY_SRC.replace("_rogue", "_other")
    project = make_project(tmp_path, {"pkg/factories.py": src})
    found = keys_pass.check_cache_keys(project, reg)
    assert any("does not account for" in f.message and "impl" in f.message
               for f in found), found
    assert any("'_gone' not found" in f.message for f in found), found
    assert len(found) == 2, found


def test_cache_keys_exemption_requires_reason(tmp_path):
    reg = types.SimpleNamespace(
        PROGRAM_IDENTITY_KNOBS=("band_dtype",),
        KNOB_ALIASES={"band_dtype": ("band_dtype",)},
        FACTORY_SCAN=("pkg/factories.py",),
        PROGRAM_FACTORIES={
            ("pkg/factories.py", "_runner"): {
                "required": (),
                "exempt": {"band_dtype": "   "},
            },
            ("pkg/factories.py", "_rogue"): {
                "required": (), "exempt": {"band_dtype": "fixture"},
            },
        },
    )
    project = make_project(tmp_path, {"pkg/factories.py": FACTORY_SRC})
    found = keys_pass.check_cache_keys(project, reg)
    assert len(found) == 1 and "no reason" in found[0].message, found


# ---------------------------------------------------------------------
# pass 2: fingerprint coverage
# ---------------------------------------------------------------------

FP_REG = types.SimpleNamespace(
    FINGERPRINT_KNOBS=("band_dtype", "guard", "content"),
    FINGERPRINT_ALIASES={
        "band_dtype": ("band_dtype",),
        "guard": ("guard",),
        "content": ("sha256", "head"),
    },
    FINGERPRINT_BUILDERS={
        ("pkg/fp.py", "_fp"): {
            "required": ("band_dtype", "guard", "content"),
            "exempt": {},
        },
    },
)

FP_SRC = """\
import hashlib


def _fp(path, band_dtype):
    head = open(path, 'rb').read(64)
    return hashlib.sha256(repr((path, band_dtype, head)).encode())
"""


def test_fingerprint_unfolded_knob(tmp_path):
    project = make_project(tmp_path, {"pkg/fp.py": FP_SRC})
    found = keys_pass.check_fingerprints(project, FP_REG)
    assert len(found) == 1, found
    f = found[0]
    # missing 'guard', anchored at the builder's def line; 'content' is
    # satisfied via its aliases (sha256 call / head name)
    assert "guard" in f.message and f.line == 4 and f.path == "pkg/fp.py"


def test_fingerprint_missing_builder(tmp_path):
    project = make_project(tmp_path, {"pkg/fp.py": "x = 1\n"})
    found = keys_pass.check_fingerprints(project, FP_REG)
    assert len(found) == 1 and "not found" in found[0].message


# ---------------------------------------------------------------------
# pass 3: dtype discipline
# ---------------------------------------------------------------------

DT_REG = types.SimpleNamespace(
    DTYPE_SCAN=("ops",),
    NARROW_DTYPES=("bfloat16", "int8"),
    WIDE_DTYPES=("float32", "int32"),
    NARROW_RESOLVERS=("band_store_dtype",),
    ACCUMULATE_CALLS=("max", "maximum", "sum", "summax"),
)

DT_SRC = """\
import jax.numpy as jnp


def bad(x, w):
    y = x.astype(jnp.bfloat16)
    return jnp.maximum(y, w)


def bad_binop(x, w, band_dtype):
    from rifraf_tpu.ops.fill_pallas import band_store_dtype
    band_dt = band_store_dtype(band_dtype)
    y = x.astype(band_dt)
    return y + w


def good(x, w):
    y = x.astype(jnp.bfloat16)
    z = y.astype(jnp.float32)
    return jnp.maximum(z, w)


def good_store(ref, x):
    ref[...] = x.astype("int8")
"""


def test_dtype_narrow_into_accumulate(tmp_path):
    project = make_project(tmp_path, {"ops/kern.py": DT_SRC})
    found = dtypes_pass.check(project, DT_REG)
    lines = sorted(f.line for f in found)
    # jnp.maximum(y, ...) at line 6; y + w (cast via the
    # band_store_dtype resolver) at line 13. The re-widened value and
    # the narrow STORE produce nothing.
    assert lines == [6, 13], found
    assert all(f.pass_id == "dtype-discipline" for f in found)


# ---------------------------------------------------------------------
# pass 4: layout contracts
# ---------------------------------------------------------------------

LAYOUT_REG = types.SimpleNamespace(
    PACK_LAYOUT_FILE="ops/packed.py",
    PACK_LAYOUT_FUNC="pack_layout",
    PACK_LAYOUT=(
        ("total", ()),
        ("scores", ()),
        ("guard", ("want_guard",)),
    ),
    PACK_TAIL="guard",
    QMETA_FILES=("ops/packed.py",),
    QMETA_GATE_NAME="input_enc",
    QMETA_GATE_VALUE="packed",
)

LAYOUT_BAD = """\
def pack_layout(n, want_guard=False):
    out = {}
    o = 0

    def take(name, size):
        nonlocal o
        out[name] = (o, o + size)
        o += size

    take("total", 1)
    if want_guard:
        take("guard", n + 1)
    take("scores", n)
    return out


def build(args, in_specs, qmeta, input_enc):
    args.append(qmeta)
    return args


def kernel(a, b, *refs, input_enc="f32"):
    refs = list(refs)
    out_ref = refs.pop(0)
    qm_ref = refs.pop(0) if input_enc == "packed" else None
    return out_ref, qm_ref
"""


def test_layout_reorder_qmeta_gate_and_pop_order(tmp_path):
    project = make_project(tmp_path, {"ops/packed.py": LAYOUT_BAD})
    found = layout_pass.check(project, LAYOUT_REG)
    msgs = [(f.line, f.message) for f in found]
    # 'guard' taken at position #1 where 'scores' is expected (line 12)
    assert any(line == 12 and "expects 'scores'" in m
               for line, m in msgs), found
    # ungated args.append(qmeta) at line 18
    assert any(line == 18 and "outside an" in m
               for line, m in msgs), found
    # the packed-gated refs.pop(0) is the SECOND pop (line 25)
    assert any(line == 25 and "FIRST pop" in m
               for line, m in msgs), found


LAYOUT_GOOD = """\
def pack_layout(n, want_guard=False):
    out = {}
    o = 0

    def take(name, size):
        nonlocal o
        out[name] = (o, o + size)
        o += size

    take("total", 1)
    take("scores", n)
    if want_guard:
        take("guard", n + 1)
    return out


def build(args, in_specs, qmeta, spec, input_enc):
    if input_enc == "packed":
        in_specs.append(spec)
        args.append(qmeta)
    return args


def kernel(a, b, *refs, input_enc="f32"):
    refs = list(refs)
    qm_ref = refs.pop(0) if input_enc == "packed" else None
    out_ref = refs.pop(0)
    return out_ref, qm_ref
"""


def test_layout_clean_fixture(tmp_path):
    project = make_project(tmp_path, {"ops/packed.py": LAYOUT_GOOD})
    assert layout_pass.check(project, LAYOUT_REG) == []


def test_layout_guard_not_last(tmp_path):
    reg = types.SimpleNamespace(
        **{**vars(LAYOUT_REG),
           "PACK_LAYOUT": (("total", ()), ("guard", ("want_guard",)),
                           ("scores", ()))})
    src = LAYOUT_GOOD.replace(
        '    take("scores", n)\n    if want_guard:\n'
        '        take("guard", n + 1)\n',
        '    if want_guard:\n        take("guard", n + 1)\n'
        '    take("scores", n)\n')
    project = make_project(tmp_path, {"ops/packed.py": src})
    found = layout_pass.check(project, reg)
    # order now matches this (deliberately wrong) registry, so only the
    # guard-tail rule fires: guard must be LAST regardless
    assert len(found) == 1 and "LAST" in found[0].message, found


# ---------------------------------------------------------------------
# pass 5: env gates
# ---------------------------------------------------------------------

# built by concat so the real env-gates pass (which scans tests/ for
# whole-string constants) does not see a gate name in this file
KNOWN_GATE = "RIFRAF_TPU_" + "KNOWN"


def test_env_gate_unregistered(tmp_path):
    reg = types.SimpleNamespace(
        ENV_SCAN=("pkg",),
        ENV_SKIP=(),
        ENV_GATES={KNOWN_GATE: "docs/envs.md"},
    )
    project = make_project(tmp_path, {
        "pkg/mod.py": """\
            import os

            KNOWN = os.environ.get("RIFRAF_TPU_KNOWN", "")
            BAD = os.environ.get("RIFRAF_TPU_UNREGISTERED", "")
        """,
        "docs/envs.md": "RIFRAF_TPU_KNOWN does a thing\n",
    })
    found = envgates_pass.check(project, reg)
    assert len(found) == 1, found
    assert found[0].line == 4
    assert "UNREGISTERED" in found[0].message


def test_env_gate_anchor_must_mention_name(tmp_path):
    reg = types.SimpleNamespace(
        ENV_SCAN=("pkg",),
        ENV_SKIP=(),
        ENV_GATES={KNOWN_GATE: "docs/envs.md"},
    )
    project = make_project(tmp_path, {
        "pkg/mod.py": 'import os\nK = os.environ.get("RIFRAF_TPU_KNOWN")\n',
        "docs/envs.md": "nothing relevant here\n",
    })
    found = envgates_pass.check(project, reg)
    assert len(found) == 1 and "never mentions" in found[0].message


def test_env_gate_stale_registration(tmp_path):
    reg = types.SimpleNamespace(
        ENV_SCAN=("pkg",),
        ENV_SKIP=(),
        ENV_GATES={KNOWN_GATE: "docs/envs.md"},
    )
    project = make_project(tmp_path, {
        "pkg/mod.py": "x = 1\n",
        "docs/envs.md": "RIFRAF_TPU_KNOWN does a thing\n",
    })
    found = envgates_pass.check(project, reg)
    assert len(found) == 1 and "no longer read" in found[0].message


# ---------------------------------------------------------------------
# pass 6: races (static half)
# ---------------------------------------------------------------------

RACE_REG = types.SimpleNamespace(
    SHARED_STATE={
        ("pkg/shared.py", "Store"): {
            "locks": ("_lock",),
            "unguarded_ok": {"hint": "single writer fixture reason"},
            "caller_locked": {},
        },
    },
)

RACE_SRC = """\
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.data = {}
        self.hint = None
        self.n = 0

    def good(self, k, v):
        with self._lock:
            self.data[k] = v
            self.n += 1

    def bad_item(self, k, v):
        self.data[k] = v

    def bad_call(self, k):
        self.data.pop(k, None)

    def bad_rebind(self):
        self.n = 0

    def ok_allowlisted(self):
        self.hint = "x"
"""


def test_races_static_flags_unguarded_writes(tmp_path):
    project = make_project(tmp_path, {"pkg/shared.py": RACE_SRC})
    found = races_pass.check(project, RACE_REG)
    lines = sorted(f.line for f in found)
    # bad_item (17), bad_call (20), bad_rebind (23); __init__, the
    # lock-guarded writes, and the allowlisted attribute stay clean
    assert lines == [17, 20, 23], found
    assert all(f.pass_id == "races" for f in found)


def test_races_allowlist_requires_reason(tmp_path):
    reg = types.SimpleNamespace(
        SHARED_STATE={
            ("pkg/shared.py", "Store"): {
                "locks": ("_lock",),
                "unguarded_ok": {"hint": "", "data": "fixture reason",
                                 "n": "fixture reason"},
                "caller_locked": {},
            },
        },
    )
    project = make_project(tmp_path, {"pkg/shared.py": RACE_SRC})
    found = races_pass.check(project, reg)
    assert len(found) == 1, found
    assert "'hint'" in found[0].message and "no reason" in found[0].message


# ---------------------------------------------------------------------
# suppression mechanism
# ---------------------------------------------------------------------

# assembled by concat so the Suppressions scanner (line-regex over raw
# source, including lines inside string literals) ignores THIS file
def _suppress_marker(passes, reason=None):
    tail = f" -- {reason}" if reason else ""
    return "# rifraf-lint: " + "disable=" + passes + tail


def test_suppression_with_reason_silences(tmp_path):
    src = RACE_SRC.replace(
        "        self.data[k] = v\n\n    def bad_call",
        "        self.data[k] = v  "
        + _suppress_marker("races", "fixture")
        + "\n\n    def bad_call",
    )
    project = make_project(tmp_path, {"pkg/shared.py": src})
    sf = project.file("pkg/shared.py")
    found = races_pass.check(project, RACE_REG)
    kept = [f for f in found
            if not sf.suppress.active(f.line, f.pass_id)]
    assert sorted(f.line for f in found) == [17, 20, 23]
    assert sorted(f.line for f in kept) == [20, 23]
    assert sf.suppress.missing_reason == []


def test_suppression_without_reason_is_a_finding(tmp_path):
    project = make_project(tmp_path, {
        "pkg/mod.py": "x = 1  " + _suppress_marker("races") + "\n"})
    sf = project.file("pkg/mod.py")
    assert len(sf.suppress.missing_reason) == 1
    line, passes = sf.suppress.missing_reason[0]
    assert line == 1 and passes == {"races"}


def test_standalone_suppression_applies_to_next_line(tmp_path):
    project = make_project(tmp_path, {
        "pkg/mod.py": _suppress_marker("env-gates", "fixture reason")
        + "\nX = 2\n"})
    sf = project.file("pkg/mod.py")
    assert sf.suppress.active(2, "env-gates")
    assert not sf.suppress.active(1, "env-gates")


def test_multi_pass_suppression(tmp_path):
    project = make_project(tmp_path, {
        "pkg/mod.py": "x = 1  " + _suppress_marker("races,layout", "r")
        + "\n"})
    sf = project.file("pkg/mod.py")
    assert sf.suppress.active(1, "races")
    assert sf.suppress.active(1, "layout")
    assert not sf.suppress.active(1, "env-gates")


# ---------------------------------------------------------------------
# the real tree: zero findings, CLI exit codes
# ---------------------------------------------------------------------

def test_clean_tree_zero_findings():
    report = run_all(repo_root())
    assert [str(f) for f in report["findings"]] == []
    assert set(report["per_pass"]) == set(PASS_IDS)
    assert report["wall_s"] > 0


def test_planted_violation_fails_the_cli(tmp_path):
    from rifraf_tpu.analysis.__main__ import main

    pkg = tmp_path / "rifraf_tpu"
    pkg.mkdir()
    # implicit concat: ONE whole-string constant in the written file
    (pkg / "mod.py").write_text(
        'X = "RIFRAF_TPU" "_NOT_REGISTERED"\n')
    report = run_all(tmp_path, passes=["env-gates"])
    assert any("NOT_REGISTERED" in str(f) for f in report["findings"])
    assert main(["--root", str(tmp_path), "--passes", "env-gates"]) == 1


def test_cli_clean_tree_exits_zero():
    from rifraf_tpu.analysis.__main__ import main

    assert main(["--root", str(repo_root())]) == 0


def test_run_all_rejects_unknown_pass():
    with pytest.raises(ValueError):
        run_all(repo_root(), passes=["bogus"])


# ---------------------------------------------------------------------
# regression: the true findings fixed in this PR
# ---------------------------------------------------------------------

def test_fold_nondefault_helper():
    from rifraf_tpu.utils import fold_nondefault

    assert fold_nondefault("input_enc", "f32", "f32") == []
    assert fold_nondefault("input_enc", "packed", "f32") == \
        ["input_enc", "packed"]
    assert fold_nondefault("guard", False, False) == []
    assert fold_nondefault("guard", True, False) == ["guard", True]
    assert fold_nondefault("verify_fraction", 0.0, 0.0) == []


def test_sweep_journal_fingerprint_bit_compat():
    """The extracted _journal_fingerprint reproduces the historical
    digests exactly: default knobs add NO parts (pre-knob journals stay
    resumable), non-default knobs append the same labeled pairs."""
    from rifraf_tpu.io.journal import fingerprint
    from rifraf_tpu.parallel.sweep_sharded import (
        _content_digest,
        _journal_fingerprint,
    )

    base = dict(G=0, infos=[], clusters=[], max_iters=10, min_dist=9,
                bandwidth_pvalue=0.1, len_bucket=64, cluster_chunk=0,
                scheduler="bucketed", read_bucket=8, band_bucket=8,
                do_alignment_proposals=True, lane_target=128,
                segment_pack=False, segment_align=False,
                band_dtype="f32", band_growth="double")
    legacy_parts = (0, [], _content_digest([]), 10, 9, 0.1, 64, 0,
                    "bucketed", 8, 8, True, 128, False, False,
                    "f32", "double")
    assert _journal_fingerprint(
        **base, guard=False, verify_fraction=0.0, input_enc="f32",
    ) == fingerprint(*legacy_parts)
    assert _journal_fingerprint(
        **base, guard=True, verify_fraction=0.0, input_enc="f32",
    ) == fingerprint(*legacy_parts, "guard", True)
    assert _journal_fingerprint(
        **base, guard=False, verify_fraction=0.25, input_enc="packed",
    ) == fingerprint(*legacy_parts, "verify_fraction", 0.25,
                     "input_enc", "packed")


def test_spool_fingerprint_covers_integrity_knobs(tmp_path):
    """The true finding this suite surfaced: the spool fingerprint
    ignored guard/verify_fraction, so a journal written by a guarded
    serve run was resumable by an unguarded one (silently skipping its
    checks). Now each non-default integrity knob changes the digest
    while the all-defaults digest matches the historical formula (old
    spool journals stay valid)."""
    import hashlib
    import os as _os
    import types as _types

    from rifraf_tpu.cli.serve import _spool_fingerprint
    from rifraf_tpu.io.journal import fingerprint
    from rifraf_tpu.serve.request import ServeConfig

    spool = tmp_path / "reqs.jsonl"
    spool.write_text('{"id": "a", "seqs": ["ACG"]}\n')
    args = _types.SimpleNamespace(phred_cap=0, deadline_ms=0,
                                  max_iters=20,
                                  alignment_proposals=True)
    cfg = ServeConfig()
    legacy = fingerprint(
        _os.path.basename(str(spool)), cfg.scores, 0, 0, 20, True,
        hashlib.sha256(spool.read_bytes()).hexdigest(),
        cfg.band_dtype, cfg.band_growth,
    )
    fp_default = _spool_fingerprint(str(spool), args, cfg)
    assert fp_default == legacy

    guarded = _spool_fingerprint(
        str(spool), args, ServeConfig(guard=True))
    verified = _spool_fingerprint(
        str(spool), args, ServeConfig(verify_fraction=0.5))
    assert len({fp_default, guarded, verified}) == 3
