"""Speculative multi-edit proposal evaluation (engine.device_loop):
composer separation properties, coordinate-remap exactness, packed
layout invariance, and spec-vs-serial driver/sweep bit-identity.

The CI kernels job runs this file under both RIFRAF_TPU_FUSED_IMPL
legs with no marker filter (slow included); tier-1 picks up only the
fast unit tests."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from rifraf_tpu.engine import device_loop as dl
from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams, check_params
from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _random_candidates(rng, Tmax, n_good, max_pos=None):
    """A cand_flat vector in _flat_candidates layout (4 ins@0 slots then
    Tmax blocks of [4 subs, 1 del, 4 ins_next]) with ``n_good``
    improving slots at random positions; everything else NEG. With
    ``max_pos`` the improving slots stay in the first ``max_pos``
    blocks so every decoded edit lands well inside a shorter live
    template."""
    n = 4 + Tmax * 9
    hi = 4 + (max_pos if max_pos is not None else Tmax) * 9
    flat = np.full((n,), dl.NEG)
    idx = rng.choice(hi, size=min(n_good, hi), replace=False)
    flat[idx] = rng.uniform(0.1, 5.0, size=len(idx))
    return jnp.asarray(flat)


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("min_dist", [5, 9, 15])
def test_composite_separation(seed, min_dist):
    """The speculative layer-2 set is disjoint from layer 1, keeps
    ``near_radius`` clear of every layer-1 anchor, and enforces the
    full min_dist among its own picks — for both the composite radius
    (SPEC_NEAR_RADIUS) and the single-best radius-2 floor."""
    rng = np.random.default_rng(seed)
    Tmax = 64
    cand = _random_candidates(rng, Tmax, n_good=60)
    vals, ok, kind, pos, base, anchor, keep, n_imp = dl._choose_parts(
        cand, min_dist
    )
    ok_h, anchor_h, keep_h = map(np.asarray, (ok, anchor, keep))
    assert np.any(keep_h)
    for near in (2, dl.SPEC_NEAR_RADIUS):
        keep2 = np.asarray(
            dl._choose_next_set(ok, anchor, keep, min_dist,
                                near_radius=near)
        )
        assert not np.any(keep2 & keep_h)
        assert np.all(ok_h[keep2])
        a1 = anchor_h[keep_h]
        a2 = anchor_h[keep2]
        if len(a2) and len(a1):
            assert np.abs(a2[:, None] - a1[None, :]).min() >= near
        if len(a2) > 1:
            d = np.abs(a2[:, None] - a2[None, :])
            np.fill_diagonal(d, 10**9)
            assert d.min() >= min_dist
    # (no size monotonicity across radii: a radius-2 walk can admit an
    # early near candidate that then min-dist-blocks several later
    # ones — only the separation invariants above are guaranteed)


def test_near_radius_floor():
    """Radii below 2 would break the _remap_pos exactness argument and
    must be rejected outright."""
    z = jnp.zeros((dl.CAP,), jnp.int32)
    with pytest.raises(AssertionError):
        dl._choose_next_set(z > 0, z, z > 0, 9, near_radius=1)


@pytest.mark.parametrize("seed", range(6))
def test_two_stage_apply_matches_union(seed):
    """The composite's defining identity: applying layer 1 and then the
    remapped layer 2 reproduces a single _apply of the union on
    original coordinates — and the result respects Tmax."""
    rng = np.random.default_rng(100 + seed)
    Tmax, tlen, min_dist = 96, 64, 7
    tmpl = np.zeros(Tmax, np.int8)
    tmpl[:tlen] = rng.integers(0, 4, tlen)
    tmpl = jnp.asarray(tmpl)
    cand = _random_candidates(rng, Tmax, n_good=80, max_pos=tlen - 4)
    vals, ok, kind, pos, base, anchor, keep, _ = dl._choose_parts(
        cand, min_dist
    )
    keep2 = dl._choose_next_set(ok, anchor, keep, min_dist, near_radius=2)

    t1, l1 = dl._apply(tmpl, tlen, kind, pos, base, keep, Tmax)
    inc, exc = dl._indel_shifts(tlen, kind, pos, keep, Tmax)
    pos_r = dl._remap_pos(pos, inc, exc)
    sep = bool(dl._spec_sep_ok(kind, pos_r, keep2, Tmax))
    t2, l2 = dl._apply(t1, l1, kind, pos_r, base, keep2, Tmax)
    tu, lu = dl._apply(tmpl, tlen, kind, pos, base, keep | keep2, Tmax)

    n_ins2 = int(np.sum(np.asarray(keep2) & (np.asarray(kind) == 2)))
    n_del2 = int(np.sum(np.asarray(keep2) & (np.asarray(kind) == 1)))
    assert int(l2) == int(l1) + n_ins2 - n_del2
    assert int(l2) <= Tmax
    assert sep  # min_dist 7 >= 4: the floor can never be crossed
    assert int(l2) == int(lu)
    assert np.array_equal(
        np.asarray(t2)[: int(l2)], np.asarray(tu)[: int(lu)]
    )


def test_spec_sep_ok_cases():
    """Direct accept/reject cases for the post-remap separation guard
    (sub/del anchor = pos+1, ins anchor = pos; pairwise floor 2)."""
    Tmax = 32

    def run(edits):
        kind = np.zeros(dl.CAP, np.int32)
        pos = np.zeros(dl.CAP, np.int32)
        keep2 = np.zeros(dl.CAP, bool)
        for i, (k, p) in enumerate(edits):
            kind[i], pos[i], keep2[i] = k, p, True
        return bool(
            dl._spec_sep_ok(jnp.asarray(kind), jnp.asarray(pos),
                            jnp.asarray(keep2), Tmax)
        )

    assert run([])  # empty composite is trivially valid
    assert run([(0, 5)])
    assert run([(0, 5), (0, 7)])  # anchors 6, 8
    assert not run([(0, 5), (0, 6)])  # anchors 6, 7: gap 1
    assert not run([(0, 5), (2, 6)])  # sub anchor 6 == ins anchor 6
    assert run([(0, 5), (2, 8)])  # anchors 6, 8
    assert run([(2, 0), (1, 1)])  # ins@0 (anchor 0) vs del@1 (anchor 2)


def test_packed_layout_front_offsets_identical():
    """speculate_k=0 rows keep the byte-identical legacy layout; the
    speculative tail is strictly appended."""
    rng = np.random.default_rng(7)
    H, Tmax = 3, 5
    hlen = rng.integers(1, Tmax + 1, H).astype(float)
    hist = rng.integers(0, 4, H * Tmax).astype(float)
    tmpl = rng.integers(0, 4, Tmax).astype(float)
    base = np.concatenate([[4.0, 1.25, 3.0, 1.0, 0.5], hlen, hist, tmpl])
    spec = np.concatenate([base, [11.0, 4.0]])

    a = dl.unpack_stage_packed(base, H, Tmax)
    b = dl.unpack_stage_packed(spec, H, Tmax, speculate=True)
    assert len(a) == 8 and len(b) == 10
    for x, y in zip(a, b[:8]):
        if isinstance(x, np.ndarray):
            assert np.array_equal(x, y)
        else:
            assert x == y
    assert b[8] == 11 and b[9] == 4


def test_validation_errors():
    """Bad speculate_k is rejected at every entry point."""
    with pytest.raises(ValueError, match="speculate_k"):
        dl.make_stage_runner(None, do_indels=True, min_dist=9, H=4,
                             Tmax=16, stop_on_same=False, speculate_k=3)
    with pytest.raises(ValueError, match="spec_step_fn"):
        dl.make_stage_runner(None, do_indels=True, min_dist=9, H=4,
                             Tmax=16, stop_on_same=False, speculate_k=1)
    params = RifrafParams(speculate_k=3)
    with pytest.raises(ValueError, match="speculate_k"):
        check_params(params.scores, 0, params)

    from rifraf_tpu.parallel.sweep_sharded import ChunkExecutor
    with pytest.raises(ValueError, match="speculate_k"):
        ChunkExecutor(speculate_k=5)


def _sampled_run(nseqs, length, error_rate, seed, dap, speculate_k):
    rng = np.random.default_rng(seed)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=nseqs, length=length, error_rate=error_rate, rng=rng,
        seq_errors=SEQ_ERRORS,
    )
    log_ps = [phred_to_log_p(np.asarray(p, float)) for p in phreds]
    return rifraf(
        seqs, error_log_ps=log_ps,
        params=RifrafParams(batch_size=0, batch_fixed=False,
                            do_alignment_proposals=dap,
                            device_loop="on", speculate_k=speculate_k),
    )


def test_spec_metadata_small():
    """Fast leg: a tiny run carries the speculation metadata block in
    both modes and k=2 reproduces serial exactly."""
    base = _sampled_run(8, 60, 0.04, seed=3, dap=False, speculate_k=0)
    spec = _sampled_run(8, 60, 0.04, seed=3, dap=False, speculate_k=2)
    assert np.array_equal(base.consensus, spec.consensus)
    assert np.isclose(base.state.score, spec.state.score,
                      rtol=1e-12, atol=1e-9)
    m0 = base.metadata["speculation"]
    m2 = spec.metadata["speculation"]
    assert not m0["enabled"] and m0["k"] == 0 and m0["attempts"] == 0
    assert m2["enabled"] and m2["k"] == 2
    assert 0 <= m2["hits"] <= m2["attempts"]
    assert m2["hit_rate"] == (
        m2["hits"] / m2["attempts"] if m2["attempts"] else 0.0
    )
    for st in m2["stages"].values():
        assert st["rounds"] == st["iterations"] - st["hits"]


@pytest.mark.slow
@pytest.mark.parametrize("dap", [False, True])
@pytest.mark.parametrize("k", [1, 2])
def test_driver_spec_equals_serial(dap, k):
    """A speculative run is bit-identical to the serial driver —
    consensus, score, and per-stage iteration counts — whether rounds
    hit or miss, under both proposal-gating modes."""
    base = _sampled_run(24, 120, 0.05, seed=205, dap=dap, speculate_k=0)
    spec = _sampled_run(24, 120, 0.05, seed=205, dap=dap, speculate_k=k)
    assert np.array_equal(base.consensus, spec.consensus)
    assert np.isclose(base.state.score, spec.state.score,
                      rtol=1e-12, atol=1e-9)
    assert np.array_equal(base.state.stage_iterations,
                          spec.state.stage_iterations)
    m = spec.metadata["speculation"]
    assert m["enabled"] and m["k"] == k
    assert m["stages"]  # the device loop ran and was accounted
    total_rounds = sum(st["rounds"] for st in m["stages"].values())
    total_iters = sum(st["iterations"] for st in m["stages"].values())
    assert total_rounds == total_iters - m["hits"]


@pytest.mark.slow
def test_sweep_speculate_matches_serial():
    """The sharded sweep path: speculate_k=2 returns the same
    consensus/score/iterations per cluster, and SweepStats reports the
    speculative lanes as overhead."""
    from rifraf_tpu.models.sequences import make_read_scores
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded

    rng = np.random.default_rng(11)
    params = RifrafParams()
    clusters = []
    for _ in range(3):
        # enough reads that the (2+k)-tiled lanes spill past one
        # 128-lane slot — spec_overhead_lanes counts whole lane slots
        _, _, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=12, length=70, error_rate=0.03, rng=rng,
            seq_errors=SEQ_ERRORS,
        )
        clusters.append([
            make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                             params.bandwidth, params.scores)
            for s, p in zip(seqs, phreds)
        ])

    # segment-packed buckets spend the segment axis on cluster packing
    # and never speculate; force per-cluster stage programs so the
    # speculative path actually engages on these tiny clusters
    res0 = sweep_clusters_sharded(clusters, segment_pack=False)
    res1, stats = sweep_clusters_sharded(clusters, speculate_k=2,
                                         segment_pack=False,
                                         return_stats=True)
    for g, (a, b) in enumerate(zip(res0, res1)):
        assert np.array_equal(a.consensus, b.consensus), g
        assert np.isclose(a.score, b.score, rtol=1e-12, atol=1e-9), g
        assert a.n_iters == b.n_iters, g
    assert stats.speculate_k == 2
    assert stats.spec_attempts > 0
    assert 0 <= stats.spec_hits <= stats.spec_attempts
    assert stats.spec_overhead_lanes > 0
