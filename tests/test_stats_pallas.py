"""Bit-identity tests for the on-core reverse-sweep stats kernel.

ops.stats_pallas consumes the fill kernel's in-kernel move codes in the
uniform band frame and must reproduce dense_pallas.stats_from_moves —
the XLA moves-scan oracle (itself oracle-tested against the vmapped
host traceback) — EXACTLY: same n_errors, same per-column edit
indicator table, across band geometries (read-length spread, bandwidth
growth, short-vs-long lane mixes), in both the single-launch int32
layout and the int8 panel-store layout. The kernels run in Pallas
interpret mode here (the suite forces the CPU backend), so tracing is
slow and the sweep tests are marked slow; the CI `kernels` job runs
them explicitly.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax, dense_pallas, fill_pallas, stats_pallas

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))


def _problem(tlen=24, n_reads=4, bw=5, seed=3, spread=5):
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(max(4, tlen - spread), tlen + spread + 1))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, bw, SCORES))
    return template, batch_reads(reads, dtype=np.float32)


def _setup(template, batch):
    tlen = len(template)
    geom = align_jax.batch_geometry(batch, tlen)
    K = fill_pallas.uniform_band_height(
        np.asarray(geom.offset), np.asarray(geom.nd)
    )
    Tmax = ((tlen + 63) // 64) * 64
    T1p = Tmax + 64
    tpl = np.zeros(Tmax, np.int8)
    tpl[:tlen] = template
    Npad = ((batch.n_reads + 127) // 128) * 128
    bufs = fill_pallas.build_fill_buffers(
        jnp.asarray(batch.seq), jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
        jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
    )
    return tlen, geom, K, Tmax, T1p, tpl, Npad, bufs


def _oracle_and_kernel(template, batch, C):
    """Run one forward fill with move recording, then both stats
    engines on the SAME move band; returns (oracle, kernel) pairs."""
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs = _setup(template, batch)
    T1 = Tmax + 1
    p = fill_pallas.prepare_fill(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom, K, T1p, C,
        with_backward=True,
    )
    NB = Npad // fill_pallas.LANES
    _, _, moves_flat = fill_pallas._fill_call(
        p["tlen_s"], p["off_s"], p["t_cols"], p["meta"], *p["tabs"],
        K=K, T1p=T1p, NBLK=2 * NB, C=C, want_moves=True, interpret=True,
    )
    moves = dense_pallas._moves_band(moves_flat, K, T1p, Npad)
    nerr_x, edits_x = dense_pallas.stats_from_moves(
        moves[:, :, :T1], bufs.seq_T.T, jnp.asarray(tpl), geom,
        bufs.lengths, K,
    )
    nerr_p, edits_p = stats_pallas.traceback_stats_pallas(
        p, moves_flat, K, T1p, C, Npad, T1, interpret=True,
    )
    return (nerr_x, edits_x), (nerr_p, edits_p), (p, moves_flat, K, T1p,
                                                  Npad, T1)


# length spread, bandwidth growth, block widths, and a wide short/long
# lane mix — the geometries the uniform frame must mask correctly
GEOMETRIES = [
    dict(tlen=24, n_reads=4, bw=5, seed=3, spread=5, C=8),
    dict(tlen=16, n_reads=3, bw=4, seed=11, spread=5, C=4),
    dict(tlen=40, n_reads=6, bw=4, seed=13, spread=5, C=16),
    dict(tlen=30, n_reads=5, bw=8, seed=21, spread=12, C=8),
    dict(tlen=48, n_reads=7, bw=6, seed=5, spread=30, C=8),
]


@pytest.mark.slow
@pytest.mark.parametrize("cfg", GEOMETRIES,
                         ids=[f"g{i}" for i in range(len(GEOMETRIES))])
def test_stats_kernel_bit_identical_to_xla(cfg):
    cfg = dict(cfg)
    C = cfg.pop("C")
    template, batch = _problem(**cfg)
    (nerr_x, edits_x), (nerr_p, edits_p), _ = _oracle_and_kernel(
        template, batch, C
    )
    np.testing.assert_array_equal(np.asarray(nerr_p), np.asarray(nerr_x))
    np.testing.assert_array_equal(np.asarray(edits_p),
                                  np.asarray(edits_x))


@pytest.mark.slow
def test_stats_kernel_nerr_only_path():
    """want_edits=False (the adapt round's shape) must agree on n_errors
    and return no edits table."""
    template, batch = _problem()
    (nerr_x, _), _, (p, moves_flat, K, T1p, Npad, T1) = (
        _oracle_and_kernel(template, batch, 8)
    )
    nerr, edits = stats_pallas.traceback_stats_pallas(
        p, moves_flat, K, T1p, 8, Npad, T1, want_edits=False,
        interpret=True,
    )
    assert edits is None
    np.testing.assert_array_equal(np.asarray(nerr), np.asarray(nerr_x))


@pytest.mark.slow
def test_fused_stats_env_opt_out_identical(monkeypatch):
    """fused_tables_pallas(want_stats=True) must produce identical
    n_errors/edits whether the stats step runs on-core (default) or on
    the XLA moves-scan path (RIFRAF_TPU_STATS_IMPL=xla)."""
    template, batch = _problem(tlen=24, n_reads=4, bw=5, seed=7)
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs = _setup(template, batch)
    weights = jnp.ones(batch.n_reads, jnp.float32)

    def run():
        return dense_pallas.fused_tables_pallas(
            jnp.asarray(tpl), jnp.int32(tlen), bufs, geom, weights,
            K, T1p, 8, want_stats=True, interpret=True,
        )

    monkeypatch.delenv("RIFRAF_TPU_STATS_IMPL", raising=False)
    assert stats_pallas.use_pallas_stats()
    on_core = run()
    monkeypatch.setenv("RIFRAF_TPU_STATS_IMPL", "xla")
    assert not stats_pallas.use_pallas_stats()
    xla = run()
    np.testing.assert_array_equal(np.asarray(on_core["n_errors"]),
                                  np.asarray(xla["n_errors"]))
    np.testing.assert_array_equal(np.asarray(on_core["edits"]),
                                  np.asarray(xla["edits"]))
    # the non-stats tables must be untouched by the stats engine choice
    np.testing.assert_array_equal(np.asarray(on_core["total"]),
                                  np.asarray(xla["total"]))


@pytest.mark.slow
def test_panel_stats_int8_matches_single_launch():
    """The panel path re-reads the stored int8 move band; its chained
    reverse-carry sweep must equal the single-launch int32 kernel."""
    template, batch = _problem(tlen=40, n_reads=3, bw=4, seed=13)
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs = _setup(template, batch)
    assert stats_pallas.int8_moves_ok(K, 8)
    weights = jnp.ones(batch.n_reads, jnp.float32)
    one = dense_pallas.fused_tables_pallas(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom, weights,
        K, T1p, 8, want_stats=True, interpret=True,
    )
    pan = dense_pallas.fused_tables_pallas_panels(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom, weights,
        K, T1p, 8, panel_cols=16, want_stats=True, interpret=True,
    )
    np.testing.assert_array_equal(np.asarray(pan["n_errors"]),
                                  np.asarray(one["n_errors"]))
    np.testing.assert_array_equal(np.asarray(pan["edits"]),
                                  np.asarray(one["edits"]))


def test_int8_moves_tile_guard():
    """The panel stats kernel loads int8 moves as (C*K, 128) blocks;
    int8 tiles need 32-row multiples."""
    assert stats_pallas.int8_moves_ok(16, 8)  # 128 rows
    assert stats_pallas.int8_moves_ok(24, 8)  # 192 rows
    assert stats_pallas.int8_moves_ok(8, 4)  # 32 rows
    assert not stats_pallas.int8_moves_ok(8, 1)  # 8 rows
    assert not stats_pallas.int8_moves_ok(24, 1)  # 24 rows


def test_use_pallas_stats_env_switch(monkeypatch):
    monkeypatch.delenv("RIFRAF_TPU_STATS_IMPL", raising=False)
    assert stats_pallas.use_pallas_stats()
    monkeypatch.setenv("RIFRAF_TPU_STATS_IMPL", "pallas")
    assert stats_pallas.use_pallas_stats()
    monkeypatch.setenv("RIFRAF_TPU_STATS_IMPL", "xla")
    assert not stats_pallas.use_pallas_stats()
