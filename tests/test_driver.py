"""Driver integration tests.

Ports the reference's full-model strategy (test_model.jl:325-375): simulated
reads must recover the exact template across a parameter grid, plus unit
coverage for proposal generation, stage logic, and quality estimation.
"""

import itertools

import numpy as np
import pytest

from rifraf_tpu.engine.driver import (
    alignment_error_probs,
    calibrate_phreds,
    correct_shifts,
    estimate_point_probs,
    rifraf,
)
from rifraf_tpu.engine.generate import (
    all_proposals,
    has_single_indels,
    single_indel_proposals,
)
from rifraf_tpu.engine.params import RifrafParams, Stage
from rifraf_tpu.engine.proposals import Deletion, Insertion, Substitution
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.constants import decode_seq, encode_seq
from rifraf_tpu.utils.phred import phred_to_log_p


def test_all_proposals_counts():
    consensus = encode_seq("ACGT")
    props = all_proposals(Stage.INIT, consensus, False)
    subs = [p for p in props if isinstance(p, Substitution)]
    inss = [p for p in props if isinstance(p, Insertion)]
    dels = [p for p in props if isinstance(p, Deletion)]
    assert len(subs) == 4 * 3
    assert len(inss) == 5 * 4
    assert len(dels) == 4
    # REFINE: substitutions only
    props = all_proposals(Stage.REFINE, consensus, False)
    assert all(isinstance(p, Substitution) for p in props)


def test_single_indel_proposals_and_has_single_indels():
    """test_model.jl:156-189 spirit: consensus with an extra base vs
    in-frame reference."""
    ref_scores = Scores.from_error_model(ErrorModel(10.0, 1e-1, 1e-1, 1.0, 1.0))
    reference = encode_seq("AAACCCGGG")
    consensus_good = encode_seq("AAACCCGGG")
    consensus_bad = encode_seq("AAACCCTGGG")  # one extra base
    log_ps = np.full(len(reference), -2.0)
    rs = make_read_scores(reference, log_ps, 6, ref_scores)
    assert not has_single_indels(consensus_good, rs)
    assert has_single_indels(consensus_bad, rs)
    props = single_indel_proposals(consensus_bad, rs)
    assert any(isinstance(p, Deletion) for p in props)


# the reference integration test's simulation settings (test_model.jl:330-345)
REF_SAMPLE_ERRORS = ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0)
REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)
SEQ_SCORES = Scores.from_error_model(SEQ_ERRORS)
SAMPLE_PARAMS = dict(
    ref_error_rate=0.1,
    ref_errors=REF_SAMPLE_ERRORS,
    error_rate=0.005,
    alpha=1.0,
    phred_scale=1.5,
    actual_std=3.0,
    reported_std=0.3,
    seq_errors=SEQ_ERRORS,
)


# the reference's full 2^4 x 2 = 32-combo integration grid
# (test_model.jl:346-372): every combination of use_ref x
# do_alignment_proposals x seed_indels x indel_correction_only x
# batch_size must recover the exact template. The reference samples
# fresh data per combo from one seeded stream and admits stochasticity
# (test_model.jl:326); here each combo gets its own deterministic seed
# (1234 + index) under which ALL 32 recover exactly (verified by sweep).
_GRID = [
    (i, *combo)
    for i, combo in enumerate(itertools.product(
        (True, False),  # use_ref
        (True, False),  # do_alignment_proposals
        (True, False),  # seed_indels
        (True, False),  # indel_correction_only
        (3, 6),  # batch_size
    ))
]


@pytest.mark.slow
@pytest.mark.parametrize(
    "idx,use_ref,do_alignment_proposals,seed_indels,indel_correction_only,batch_size",
    _GRID,
)
def test_full_model_recovers_template(
    idx, use_ref, do_alignment_proposals, seed_indels,
    indel_correction_only, batch_size,
):
    """Exact template recovery across the reference's full parameter grid
    (test_model.jl:325-375)."""
    rng = np.random.default_rng(1234 + idx)
    (ref, template, t_p, seqs, actual, phreds, cb, db) = sample_sequences(
        nseqs=5, length=30, rng=rng, **SAMPLE_PARAMS
    )
    params = RifrafParams(
        scores=SEQ_SCORES,
        ref_scores=REF_SCORES,
        do_alignment_proposals=do_alignment_proposals,
        seed_indels=seed_indels,
        indel_correction_only=indel_correction_only,
        batch_size=batch_size,
        seed=1234 + idx,
    )
    result = rifraf(
        seqs,
        phreds=phreds,
        reference=ref if use_ref else None,
        params=params,
    )
    assert decode_seq(result.consensus) == decode_seq(template)


def test_frame_correction_fixes_frameshift():
    """FRAME stage must repair single-base frameshifts using the
    reference (the core RIFRAF feature): after convergence the
    consensus-vs-reference alignment must contain NO single (non-codon)
    indels (the FRAME exit criterion, model.jl:532-536, 963-965) — a
    run that fixed nothing cannot pass."""
    rng = np.random.default_rng(7)
    (ref, template, t_p, seqs, actual, phreds, cb, db) = sample_sequences(
        nseqs=6, length=30, error_rate=0.08, rng=rng
    )
    result = rifraf(seqs, phreds=phreds, reference=ref, params=RifrafParams(seed=1))
    assert result.state.converged
    assert result.state.reference is not None
    assert not has_single_indels(result.consensus, result.state.reference)


@pytest.mark.slow
def test_do_score_quality_estimation():
    """Quality estimation output shapes and ranges (test_model.jl:378-449)."""
    rng = np.random.default_rng(11)
    (ref, template, t_p, seqs, actual, phreds, cb, db) = sample_sequences(
        nseqs=5, length=25, error_rate=0.03, rng=rng
    )
    params = RifrafParams(do_score=True, seed=3)
    result = rifraf(seqs, phreds=phreds, params=params)
    ep = result.error_probs
    L = len(result.consensus)
    assert ep.sub.shape == (L, 4)
    assert ep.dele.shape == (L,)
    assert ep.ins.shape == (L + 1, 4)
    assert (ep.sub >= 0).all() and (ep.sub <= 1).all()
    assert (ep.dele >= 0).all() and (ep.dele <= 1).all()
    assert (ep.ins >= 0).all() and (ep.ins <= 1).all()
    point = estimate_point_probs(ep)
    assert point.shape == (L,)
    assert (point >= 0).all() and (point <= 1).all()
    assert result.aln_error_probs.shape == (L,)


def test_estimate_probs_table_readout_matches_proposal_loop(monkeypatch):
    """The SCORE-stage whole-table readout (aligner.dense_score_tables)
    must equal the legacy one-proposal-at-a-time scoring loop exactly —
    identity-substitution slots included."""
    from rifraf_tpu.engine import driver as driver_mod

    rng = np.random.default_rng(11)
    (ref, template, t_p, seqs, actual, phreds, cb, db) = sample_sequences(
        nseqs=5, length=25, error_rate=0.03, rng=rng
    )
    params = RifrafParams(do_score=True, seed=3)
    result = rifraf(seqs, phreds=phreds, params=params)
    state = result.state
    assert state.aligner.dense_score_tables(len(state.consensus)) is not None
    fast = driver_mod.estimate_probs(state, params)
    monkeypatch.setattr(
        type(state.aligner), "dense_score_tables",
        lambda self, tlen: None,
    )
    slow = driver_mod.estimate_probs(state, params)
    np.testing.assert_array_equal(fast.sub, slow.sub)
    np.testing.assert_array_equal(fast.dele, slow.dele)
    np.testing.assert_array_equal(fast.ins, slow.ins)


@pytest.mark.parametrize(
    "consensus,reference,expected",
    [
        # the reference's exact golden in/out cases
        # (/root/reference/test/test_correct_shifts.jl:8-35)
        ("TTTT", "TTT", "TTT"),  # one deletion
        ("TT", "TTT", "TTT"),  # one insertion
        ("TTTACCC", "TTTCGC", "TTTCCC"),  # deletion inside
        ("TTTAAACCC", "TTTCGC", "TTTAAACCC"),  # codon indel: unchanged
    ],
)
def test_correct_shifts_golden_cases(consensus, reference, expected):
    got = correct_shifts(consensus, reference)
    assert decode_seq(got) == expected


def test_calibrate_phreds():
    consensus = encode_seq("ACGTACGT")
    seq = encode_seq("ACGTACGA")  # one error
    phred = np.full(8, 20, dtype=np.int8)
    calibrated = calibrate_phreds(seq, phred, consensus)
    np.testing.assert_allclose(calibrated.sum(), 1.0, rtol=1e-9)


def test_initial_consensus_is_best_read():
    """With max_iters=1 and no proposals possible, consensus stays at the
    highest-quality read (model.jl:575-579)."""
    seqs = [encode_seq("ACGTACGT"), encode_seq("ACGAACGT")]
    phreds = [np.full(8, 30, dtype=np.int8), np.full(8, 10, dtype=np.int8)]
    params = RifrafParams(max_iters=1, do_frame=False, do_refine=False)
    result = rifraf(seqs, phreds=phreds, params=params)
    assert len(result.consensus) == 8


def test_rifraf_requires_error_info():
    with pytest.raises(ValueError):
        rifraf([encode_seq("ACGT")])


def _noisy_reads(n=6, length=120, seed=11, error_rate=0.02):
    rng = np.random.default_rng(seed)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=n, length=length, error_rate=error_rate, rng=rng
    )
    reads = [
        make_read_scores(s, phred_to_log_p(np.asarray(p, float)), 9, SEQ_SCORES)
        for s, p in zip(seqs, phreds)
    ]
    return template, reads


@pytest.mark.slow
def test_bandwidth_cap_uses_entry_bandwidth():
    """Regression: max_bw must be computed once from the entry bandwidth
    (model.jl:650 caps at bandwidth*2^5), not recomputed from the
    already-doubled value — otherwise growth can continue past the final
    refill, leaving A and B bands at mismatched heights."""
    from rifraf_tpu.engine.realign import MAX_BANDWIDTH_DOUBLINGS, BatchAligner

    template, reads = _noisy_reads(n=2, length=400)
    for r in reads:
        r.bandwidth = 2
        r.bandwidth_fixed = False
    aligner = BatchAligner(reads)
    entry_bw = aligner.bandwidths.copy()
    cap = int(entry_bw[0]) << MAX_BANDWIDTH_DOUBLINGS
    tlen = len(template)
    # force growth every round: huge error counts, strictly decreasing
    big = 10**6
    for round_ in range(2 * (MAX_BANDWIDTH_DOUBLINGS + 2)):
        aligner._old_errors = np.full(len(reads), np.iinfo(np.int64).max)
        aligner._maybe_grow_bandwidth(
            np.full(len(reads), big - round_), tlen, 0.1, entry_bw
        )
    assert (aligner.bandwidths <= cap).all(), aligner.bandwidths


@pytest.mark.slow
def test_bandwidth_growth_never_outruns_final_refill():
    """After realign() the A and B bands must always have identical band
    heights, even when bandwidth adaptation maxes out its doublings."""
    from rifraf_tpu.engine.realign import BatchAligner

    template, reads = _noisy_reads(n=3, length=300, error_rate=0.15)
    for r in reads:
        r.bandwidth = 2
        r.bandwidth_fixed = False
    aligner = BatchAligner(reads)
    aligner.realign(template, pvalue=0.1)
    assert aligner.A_bands.shape == aligner.B_bands.shape
    assert aligner.fixed.all()


@pytest.mark.slow
def test_same_membership_resample_keeps_batch_state():
    """resample() rebuilds the batch list object each iteration, so the
    aligner must compare batch MEMBERSHIP, not list identity: an unchanged
    selection must NOT trigger a set_batch rebuild (which would reset
    adapted bandwidths and re-stage the batch arrays on device). A realign
    whose consensus, batch, and bandwidths all match the previous fill is
    memoized away entirely — zero additional dispatches or fetches (each
    fetch pays a fixed round trip on tunneled hardware)."""
    from rifraf_tpu.engine import driver as drv

    template, reads = _noisy_reads(n=6, length=90)
    params = RifrafParams(batch_fixed=True, batch_fixed_size=4)
    state = drv.initial_state(None, reads, None, params)
    rng = np.random.default_rng(0)

    drv.resample(state, params, rng)
    drv.realign_rescore(state, params)
    batch_obj = state.aligner.batch
    fills = state.aligner.n_forward_fills
    assert fills >= 1
    assert state.aligner.fixed.all()  # bandwidths settled

    # same membership, fresh list object: the device batch must be reused
    # and the settled bandwidth state must survive
    state.realign_As = False
    state.realign_Bs = True
    drv.resample(state, params, rng)
    drv.realign_rescore(state, params)
    assert state.aligner.batch is batch_obj
    assert state.aligner.fixed.all()
    # unchanged consensus + batch + bandwidths: memoized, no new fill
    assert state.aligner.n_forward_fills == fills


def test_batch_threshold_validated():
    from rifraf_tpu.engine.params import check_params

    params = RifrafParams(batch_threshold=1.5)
    with pytest.raises(ValueError, match="batch_threshold"):
        check_params(params.scores, 0, params)


@pytest.mark.slow
def test_use_ref_for_qvs_without_frame_builds_reference():
    """Regression: with do_frame=False + use_ref_for_qvs=True the SCORE
    stage must never score against the placeholder reference built by
    initial_state (all-zero score vectors); the real score vectors are
    built lazily from an edit-distance error estimate."""
    rng = np.random.default_rng(5)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=5, length=60, rng=rng, **SAMPLE_PARAMS
    )
    reference = ref
    params = RifrafParams(
        do_frame=False, do_score=True, use_ref_for_qvs=True,
        ref_scores=REF_SCORES, scores=SEQ_SCORES,
    )
    result = rifraf(seqs, phreds=phreds, reference=reference, params=params)
    state = result.state
    assert state.ref_built
    # real (negative, finite) match scores — not the placeholder zeros
    assert np.all(state.reference.match_scores < 0.0)
    assert np.all(np.isfinite(state.reference.match_scores))
    assert result.error_probs is not None
    probs = estimate_point_probs(result.error_probs)
    assert probs.shape == (len(result.consensus),)
    assert np.all((probs >= 0.0) & (probs <= 1.0))


@pytest.mark.slow
def test_verbose3_dumps_consensus_and_timers(capsys):
    """verbose>=3 prints the full per-iteration consensus (model.jl:1164-
    1168); verbose>=2 prints the length line and the timer summary."""
    rng = np.random.default_rng(5)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=4, length=30, error_rate=0.02, rng=rng,
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    r = rifraf(seqs, phreds=phreds, params=RifrafParams(verbose=3))
    err = capsys.readouterr().err
    assert "  consensus: " in err
    assert "timers:" in err
    assert "realign_rescore" in err
    assert r.timers is not None
    assert r.timers.data["realign_rescore"][0] >= 1

    r2 = rifraf(seqs, phreds=phreds, params=RifrafParams(verbose=2))
    err2 = capsys.readouterr().err
    assert "  consensus length: " in err2
    assert "  consensus: " not in err2


def test_myassert_gated_by_debug():
    from rifraf_tpu.utils import debug

    debug.myassert(True, "never raises")
    with pytest.raises(AssertionError):
        debug.myassert(False, "boom")
    saved = debug.DEBUG
    try:
        debug.DEBUG = False
        debug.myassert(False, "gated off")
    finally:
        debug.DEBUG = saved
