"""Device-resident stage loop: unit semantics + host-loop equality.

The equality tests run the FULL driver twice on the CPU backend — host
per-iteration loop vs the lax.while_loop stage runner (XLA step) — and
require identical consensus, scores, per-stage iteration counts, and
per-iteration consensus history (engine.device_loop's bit-identity
contract)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from rifraf_tpu.engine import device_loop as dl
from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.engine.proposals import (
    Deletion,
    Insertion,
    ScoredProposal,
    Substitution,
    apply_proposals,
    choose_candidates,
)
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.sim.sample import sample_sequences


def _decode_host(idx):
    """Reference decode of the flat candidate layout (generation order)."""
    if idx < 4:
        return Insertion(0, idx)
    r = idx - 4
    j, k = divmod(r, 9)
    if k < 4:
        return Substitution(j, k)
    if k == 4:
        return Deletion(j)
    return Insertion(j + 1, k - 5)


def test_decode_matches_generation_order():
    """The flat layout must enumerate proposals exactly as
    engine.generate.all_proposals emits them (ties in choose_candidates
    break by this order)."""
    from rifraf_tpu.engine.generate import all_proposals
    from rifraf_tpu.engine.params import Stage

    consensus = np.array([0, 1, 2, 3, 1], dtype=np.int8)
    want = all_proposals(Stage.INIT, consensus, False)
    got = []
    for idx in range(4 + 9 * len(consensus)):
        p = _decode_host(idx)
        if isinstance(p, Substitution) and consensus[p.pos] == p.base:
            continue  # masked own-base slot
        got.append(p)
    assert got == want

    kind, pos, base, anchor = (np.asarray(v) for v in dl._decode(
        jnp.arange(4 + 9 * len(consensus))
    ))
    from rifraf_tpu.engine.proposals import anchor as host_anchor

    for idx in range(4 + 9 * len(consensus)):
        p = _decode_host(idx)
        want_kind = {Substitution: 0, Deletion: 1, Insertion: 2}[type(p)]
        assert kind[idx] == want_kind, idx
        assert pos[idx] == p.pos, idx
        if not isinstance(p, Deletion):
            assert base[idx] == p.base, idx
        assert anchor[idx] == host_anchor(p), idx


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 7, 19])
def test_apply_matches_host_apply_proposals(seed):
    """_apply == apply_proposals for random min-dist-separated sets."""
    rng = np.random.default_rng(seed)
    Tmax = 64
    tlen = int(rng.integers(20, 50))
    tmpl = rng.integers(0, 4, size=Tmax).astype(np.int8)
    # build a random min-dist-separated proposal set via the real filter
    cands = []
    for idx in rng.permutation(4 + 9 * tlen - 9)[:40]:
        cands.append(ScoredProposal(_decode_host(int(idx)),
                                    float(rng.normal())))
    chosen = choose_candidates(cands, 6)
    want = apply_proposals(tmpl[:tlen], [c.proposal for c in chosen])

    kind = np.zeros(dl.CAP, np.int32)
    pos = np.zeros(dl.CAP, np.int32)
    base = np.zeros(dl.CAP, np.int32)
    keep = np.zeros(dl.CAP, bool)
    for i, c in enumerate(chosen):
        p = c.proposal
        kind[i] = {Substitution: 0, Deletion: 1, Insertion: 2}[type(p)]
        pos[i] = p.pos
        base[i] = getattr(p, "base", 0)
        keep[i] = True
    out, new_tlen = dl._apply(
        jnp.asarray(tmpl), jnp.int32(tlen), jnp.asarray(kind),
        jnp.asarray(pos), jnp.asarray(base), jnp.asarray(keep), Tmax,
    )
    got = np.asarray(out)[: int(new_tlen)]
    np.testing.assert_array_equal(got, want)


def test_choose_matches_host_choose_candidates():
    """_choose (top-k + greedy min-dist walk) == choose_candidates on a
    dense random score vector, including tie behavior."""
    rng = np.random.default_rng(3)
    tlen = 40
    P = 4 + 9 * tlen
    scores = np.full(P, float(dl.NEG), np.float32)
    hot = rng.choice(P, size=60, replace=False)
    scores[hot] = rng.choice([1.0, 2.0, 3.0], size=60).astype(np.float32)

    min_dist = 6
    kind, pos, base, keep, n_improving, best = (
        np.asarray(v) for v in dl._choose(jnp.asarray(scores), min_dist)
    )
    got = []
    order = np.asarray(jax.lax.top_k(jnp.asarray(scores), dl.CAP)[1])
    for c in range(dl.CAP):
        if keep[c]:
            got.append(_decode_host(int(order[c])))

    cands = [
        ScoredProposal(_decode_host(int(i)), float(scores[i]))
        for i in np.nonzero(scores > float(dl.NEG))[0]
    ]
    want = [c.proposal for c in choose_candidates(cands, min_dist)]
    assert int(n_improving) == len(cands)
    assert got == want


_EQ_KW = dict(batch_size=0, batch_fixed=False, do_alignment_proposals=False)


@pytest.mark.parametrize("seed,err,use_ref", [(5, 0.08, False), (13, 0.05, True)])
def test_device_loop_matches_host_loop(seed, err, use_ref):
    """Full-driver equality: device_loop='on' must reproduce the host
    loop exactly — consensus, score, per-stage iteration counts, and the
    complete per-iteration consensus history."""
    REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
    rng = np.random.default_rng(seed)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=8, length=100, error_rate=err, rng=rng,
        ref_error_rate=0.1, ref_errors=ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0),
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    r = ref if use_ref else None
    base = rifraf(seqs, phreds=phreds, reference=r,
                  params=RifrafParams(device_loop="off", ref_scores=REF_SCORES,
                                      **_EQ_KW))
    dev = rifraf(seqs, phreds=phreds, reference=r,
                 params=RifrafParams(device_loop="on", ref_scores=REF_SCORES,
                                     **_EQ_KW))
    assert np.array_equal(base.consensus, dev.consensus)
    assert np.isclose(base.state.score, dev.state.score, rtol=1e-12)
    assert base.state.stage_iterations.tolist() == \
        dev.state.stage_iterations.tolist()
    for a, b in zip(base.consensus_stages, dev.consensus_stages):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)


@pytest.mark.slow
def test_device_loop_respects_max_iters():
    """iters_left must bound the device stage exactly like max_iters
    bounds the host loop."""
    rng = np.random.default_rng(11)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=6, length=80, error_rate=0.08, rng=rng,
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    for mi in (1, 2):
        base = rifraf(seqs, phreds=phreds,
                      params=RifrafParams(device_loop="off", max_iters=mi,
                                          **_EQ_KW))
        dev = rifraf(seqs, phreds=phreds,
                     params=RifrafParams(device_loop="on", max_iters=mi,
                                         **_EQ_KW))
        assert np.array_equal(base.consensus, dev.consensus)
        assert int(dev.state.stage_iterations.sum()) <= mi
        assert base.state.stage_iterations.tolist() == \
            dev.state.stage_iterations.tolist()
        # a budget-truncated stage must NOT report convergence
        # (finish_stage only fires when the stage ended itself)
        assert base.state.converged == dev.state.converged


@pytest.mark.slow
@pytest.mark.parametrize("seed,icorr", [(13, True), (29, False)])
def test_device_frame_matches_host_loop(seed, icorr):
    """FRAME as one device dispatch (reads step + codon reference
    tables, seed_indels=False) must reproduce the host loop exactly —
    including penalty-escalation re-entries, whose stop-on-same guard
    follows the host's penalties_increased skip."""
    REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
    rng = np.random.default_rng(seed)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=8, length=99, error_rate=0.06, rng=rng,
        ref_error_rate=0.1, ref_errors=ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0),
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    kw = dict(_EQ_KW, seed_indels=False, indel_correction_only=icorr,
              ref_scores=REF_SCORES)
    base = rifraf(seqs, phreds=phreds, reference=ref,
                  params=RifrafParams(device_loop="off", **kw))
    dev = rifraf(seqs, phreds=phreds, reference=ref,
                 params=RifrafParams(device_loop="on", **kw))
    assert np.array_equal(base.consensus, dev.consensus)
    assert np.isclose(base.state.score, dev.state.score, rtol=1e-12)
    assert base.state.stage_iterations.tolist() == \
        dev.state.stage_iterations.tolist()
    for a, b in zip(base.consensus_stages, dev.consensus_stages):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)


def _assert_runs_equal(base, dev):
    assert np.array_equal(base.consensus, dev.consensus)
    assert np.isclose(base.state.score, dev.state.score, rtol=1e-12)
    assert base.state.stage_iterations.tolist() == \
        dev.state.stage_iterations.tolist()
    for a, b in zip(base.consensus_stages, dev.consensus_stages):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)


@pytest.mark.slow
@pytest.mark.parametrize("seed,use_ref", [(5, False), (13, True), (21, True)])
def test_device_loop_matches_host_alignment_proposals(seed, use_ref):
    """do_alignment_proposals=True as a device stage: the in-kernel
    edits indicators must reproduce the host's traceback-restricted
    candidate set (engine.generate.alignment_proposals semantics)
    bit-for-bit — consensus, score, iteration counts, full history."""
    REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
    rng = np.random.default_rng(seed)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=8, length=100, error_rate=0.05, rng=rng,
        ref_error_rate=0.1, ref_errors=ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0),
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    r = ref if use_ref else None
    kw = dict(batch_size=0, batch_fixed=False, do_alignment_proposals=True,
              ref_scores=REF_SCORES)
    base = rifraf(seqs, phreds=phreds, reference=r,
                  params=RifrafParams(device_loop="off", **kw))
    dev = rifraf(seqs, phreds=phreds, reference=r,
                 params=RifrafParams(device_loop="on", **kw))
    _assert_runs_equal(base, dev)
    assert dev.metadata["stage_paths"]["INIT"] == "device_loop"


@pytest.mark.slow
def test_device_loop_matches_host_fixed_partial_batch():
    """batch_fixed's partial INIT/FRAME batch is a deterministic stable
    argsort (no rng draw), so the device loop now takes it; the host and
    device runs must still agree exactly. REFINE grows to the full batch
    only for full-batch configs, so it stays on host here."""
    REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
    rng = np.random.default_rng(3)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=8, length=100, error_rate=0.05, rng=rng,
        ref_error_rate=0.1, ref_errors=ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0),
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    kw = dict(batch_size=5, batch_fixed=True, do_alignment_proposals=True,
              ref_scores=REF_SCORES)
    base = rifraf(seqs, phreds=phreds, reference=ref,
                  params=RifrafParams(device_loop="off", **kw))
    dev = rifraf(seqs, phreds=phreds, reference=ref,
                 params=RifrafParams(device_loop="on", **kw))
    _assert_runs_equal(base, dev)
    assert dev.metadata["stage_paths"]["INIT"] == "device_loop"


@pytest.mark.slow
def test_device_frame_seed_gate_matches_host_loop():
    """seed_indels FRAME as one dispatch: the device-computed
    consensus-vs-reference anchor gate (model.jl:538-562 semantics) must
    reproduce the host's seeded candidate restriction bit-for-bit,
    including penalty-escalation re-entries. Lengths sit above
    ops.align_codon_jax.DEVICE_THRESHOLD so the host's own seed
    alignment routes through the same device engine (below it the numpy
    engine breaks score ties differently and the driver declines)."""
    REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
    rng = np.random.default_rng(17)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=6, length=600, error_rate=0.05, rng=rng,
        ref_error_rate=0.1, ref_errors=ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0),
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    kw = dict(batch_size=0, batch_fixed=False, do_alignment_proposals=True,
              seed_indels=True, ref_scores=REF_SCORES)
    base = rifraf(seqs, phreds=phreds, reference=ref,
                  params=RifrafParams(device_loop="off", **kw))
    dev = rifraf(seqs, phreds=phreds, reference=ref,
                 params=RifrafParams(device_loop="on", **kw))
    _assert_runs_equal(base, dev)
    assert dev.metadata["stage_paths"]["FRAME"] == "device_loop"


def test_seed_gate_declines_below_device_threshold():
    """Short consensus/reference: the host computes indel seeds with the
    numpy aligner, whose tie-breaking the device engine does not
    reproduce — the driver must decline the FRAME device loop and say
    why in the result metadata."""
    REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
    rng = np.random.default_rng(13)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=6, length=100, error_rate=0.05, rng=rng,
        ref_error_rate=0.1, ref_errors=ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0),
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    dev = rifraf(seqs, phreds=phreds, reference=ref,
                 params=RifrafParams(device_loop="on", batch_size=0,
                                     batch_fixed=False, seed_indels=True,
                                     do_alignment_proposals=False,
                                     ref_scores=REF_SCORES))
    path = dev.metadata["stage_paths"]["FRAME"]
    assert path.startswith("host (")
    assert "threshold" in path


def test_default_config_selects_device_loop(monkeypatch):
    """Path-selection only, no compiled equality: with pure default
    params (do_alignment_proposals=True — the reference-default
    candidate algorithm) and device_loop='on', the driver must REQUEST a
    whole-stage runner with the edits gate enabled. The stub returns
    None so nothing device-side compiles."""
    from rifraf_tpu.engine import realign as realign_mod

    calls = []
    orig = realign_mod.BatchAligner.stage_runner

    def spy(self, tlen0, do_indels, min_dist, history_cap, stop_on_same,
            use_edits=False, speculate_k=0):
        calls.append({"use_edits": use_edits, "do_indels": do_indels})
        return None

    monkeypatch.setattr(realign_mod.BatchAligner, "stage_runner", spy)
    rng = np.random.default_rng(5)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=8, length=100, error_rate=0.05, rng=rng,
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    res = rifraf(seqs, phreds=phreds,
                 params=RifrafParams(device_loop="on"))
    assert calls, "driver never requested a whole-stage runner"
    assert all(c["use_edits"] for c in calls)
    # the stub declined, so the work itself ran on host and said why
    assert res.metadata["stage_paths"]["INIT"].startswith("host (")
    assert orig is not realign_mod.BatchAligner.stage_runner


@pytest.mark.parametrize("stage_name,icorr", [
    ("INIT", False), ("REFINE", False), ("FRAME", True), ("FRAME", False),
])
def test_candidate_layout_counts_match_generate(stage_name, icorr):
    """The dense device layout and engine.generate.all_proposals must
    agree on the candidate COUNT for every (do_subs, do_indels)
    combination — ungated, uniform tables, so every live slot counts."""
    from rifraf_tpu.engine.generate import all_proposals
    from rifraf_tpu.engine.params import Stage

    stage = Stage[stage_name]
    rng = np.random.default_rng(2)
    Tmax = 48
    tlen = 37
    tmpl = rng.integers(0, 4, size=Tmax).astype(np.int8)
    do_subs = stage != Stage.FRAME or not icorr
    do_indels = stage in (Stage.INIT, Stage.FRAME)

    ones4 = jnp.ones((Tmax, 4), jnp.float32)
    cand = dl._candidate_scores(
        ones4, jnp.ones((Tmax + 1, 4), jnp.float32),
        jnp.ones((Tmax,), jnp.float32), jnp.asarray(tmpl),
        jnp.int32(tlen), jnp.float32(0.0), do_indels, Tmax,
        do_subs=do_subs,
    )
    n_live = int(np.sum(np.asarray(cand) > float(dl.NEG) / 2))
    want = len(all_proposals(stage, tmpl[:tlen], icorr))
    assert n_live == want
