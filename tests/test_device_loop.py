"""Device-resident stage loop: unit semantics + host-loop equality.

The equality tests run the FULL driver twice on the CPU backend — host
per-iteration loop vs the lax.while_loop stage runner (XLA step) — and
require identical consensus, scores, per-stage iteration counts, and
per-iteration consensus history (engine.device_loop's bit-identity
contract)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from rifraf_tpu.engine import device_loop as dl
from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.engine.proposals import (
    Deletion,
    Insertion,
    ScoredProposal,
    Substitution,
    apply_proposals,
    choose_candidates,
)
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.sim.sample import sample_sequences


def _decode_host(idx):
    """Reference decode of the flat candidate layout (generation order)."""
    if idx < 4:
        return Insertion(0, idx)
    r = idx - 4
    j, k = divmod(r, 9)
    if k < 4:
        return Substitution(j, k)
    if k == 4:
        return Deletion(j)
    return Insertion(j + 1, k - 5)


def test_decode_matches_generation_order():
    """The flat layout must enumerate proposals exactly as
    engine.generate.all_proposals emits them (ties in choose_candidates
    break by this order)."""
    from rifraf_tpu.engine.generate import all_proposals
    from rifraf_tpu.engine.params import Stage

    consensus = np.array([0, 1, 2, 3, 1], dtype=np.int8)
    want = all_proposals(Stage.INIT, consensus, False)
    got = []
    for idx in range(4 + 9 * len(consensus)):
        p = _decode_host(idx)
        if isinstance(p, Substitution) and consensus[p.pos] == p.base:
            continue  # masked own-base slot
        got.append(p)
    assert got == want

    kind, pos, base, anchor = (np.asarray(v) for v in dl._decode(
        jnp.arange(4 + 9 * len(consensus))
    ))
    from rifraf_tpu.engine.proposals import anchor as host_anchor

    for idx in range(4 + 9 * len(consensus)):
        p = _decode_host(idx)
        want_kind = {Substitution: 0, Deletion: 1, Insertion: 2}[type(p)]
        assert kind[idx] == want_kind, idx
        assert pos[idx] == p.pos, idx
        if not isinstance(p, Deletion):
            assert base[idx] == p.base, idx
        assert anchor[idx] == host_anchor(p), idx


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 7, 19])
def test_apply_matches_host_apply_proposals(seed):
    """_apply == apply_proposals for random min-dist-separated sets."""
    rng = np.random.default_rng(seed)
    Tmax = 64
    tlen = int(rng.integers(20, 50))
    tmpl = rng.integers(0, 4, size=Tmax).astype(np.int8)
    # build a random min-dist-separated proposal set via the real filter
    cands = []
    for idx in rng.permutation(4 + 9 * tlen - 9)[:40]:
        cands.append(ScoredProposal(_decode_host(int(idx)),
                                    float(rng.normal())))
    chosen = choose_candidates(cands, 6)
    want = apply_proposals(tmpl[:tlen], [c.proposal for c in chosen])

    kind = np.zeros(dl.CAP, np.int32)
    pos = np.zeros(dl.CAP, np.int32)
    base = np.zeros(dl.CAP, np.int32)
    keep = np.zeros(dl.CAP, bool)
    for i, c in enumerate(chosen):
        p = c.proposal
        kind[i] = {Substitution: 0, Deletion: 1, Insertion: 2}[type(p)]
        pos[i] = p.pos
        base[i] = getattr(p, "base", 0)
        keep[i] = True
    out, new_tlen = dl._apply(
        jnp.asarray(tmpl), jnp.int32(tlen), jnp.asarray(kind),
        jnp.asarray(pos), jnp.asarray(base), jnp.asarray(keep), Tmax,
    )
    got = np.asarray(out)[: int(new_tlen)]
    np.testing.assert_array_equal(got, want)


def test_choose_matches_host_choose_candidates():
    """_choose (top-k + greedy min-dist walk) == choose_candidates on a
    dense random score vector, including tie behavior."""
    rng = np.random.default_rng(3)
    tlen = 40
    P = 4 + 9 * tlen
    scores = np.full(P, float(dl.NEG), np.float32)
    hot = rng.choice(P, size=60, replace=False)
    scores[hot] = rng.choice([1.0, 2.0, 3.0], size=60).astype(np.float32)

    min_dist = 6
    kind, pos, base, keep, n_improving, best = (
        np.asarray(v) for v in dl._choose(jnp.asarray(scores), min_dist)
    )
    got = []
    order = np.asarray(jax.lax.top_k(jnp.asarray(scores), dl.CAP)[1])
    for c in range(dl.CAP):
        if keep[c]:
            got.append(_decode_host(int(order[c])))

    cands = [
        ScoredProposal(_decode_host(int(i)), float(scores[i]))
        for i in np.nonzero(scores > float(dl.NEG))[0]
    ]
    want = [c.proposal for c in choose_candidates(cands, min_dist)]
    assert int(n_improving) == len(cands)
    assert got == want


_EQ_KW = dict(batch_size=0, batch_fixed=False, do_alignment_proposals=False)


@pytest.mark.parametrize("seed,err,use_ref", [(5, 0.08, False), (13, 0.05, True)])
def test_device_loop_matches_host_loop(seed, err, use_ref):
    """Full-driver equality: device_loop='on' must reproduce the host
    loop exactly — consensus, score, per-stage iteration counts, and the
    complete per-iteration consensus history."""
    REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
    rng = np.random.default_rng(seed)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=8, length=100, error_rate=err, rng=rng,
        ref_error_rate=0.1, ref_errors=ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0),
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    r = ref if use_ref else None
    base = rifraf(seqs, phreds=phreds, reference=r,
                  params=RifrafParams(device_loop="off", ref_scores=REF_SCORES,
                                      **_EQ_KW))
    dev = rifraf(seqs, phreds=phreds, reference=r,
                 params=RifrafParams(device_loop="on", ref_scores=REF_SCORES,
                                     **_EQ_KW))
    assert np.array_equal(base.consensus, dev.consensus)
    assert np.isclose(base.state.score, dev.state.score, rtol=1e-12)
    assert base.state.stage_iterations.tolist() == \
        dev.state.stage_iterations.tolist()
    for a, b in zip(base.consensus_stages, dev.consensus_stages):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)


@pytest.mark.slow
def test_device_loop_respects_max_iters():
    """iters_left must bound the device stage exactly like max_iters
    bounds the host loop."""
    rng = np.random.default_rng(11)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=6, length=80, error_rate=0.08, rng=rng,
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    for mi in (1, 2):
        base = rifraf(seqs, phreds=phreds,
                      params=RifrafParams(device_loop="off", max_iters=mi,
                                          **_EQ_KW))
        dev = rifraf(seqs, phreds=phreds,
                     params=RifrafParams(device_loop="on", max_iters=mi,
                                         **_EQ_KW))
        assert np.array_equal(base.consensus, dev.consensus)
        assert int(dev.state.stage_iterations.sum()) <= mi
        assert base.state.stage_iterations.tolist() == \
            dev.state.stage_iterations.tolist()
        # a budget-truncated stage must NOT report convergence
        # (finish_stage only fires when the stage ended itself)
        assert base.state.converged == dev.state.converged


@pytest.mark.slow
@pytest.mark.parametrize("seed,icorr", [(13, True), (29, False)])
def test_device_frame_matches_host_loop(seed, icorr):
    """FRAME as one device dispatch (reads step + codon reference
    tables, seed_indels=False) must reproduce the host loop exactly —
    including penalty-escalation re-entries, whose stop-on-same guard
    follows the host's penalties_increased skip."""
    REF_SCORES = Scores.from_error_model(ErrorModel(8.0, 0.1, 0.1, 1.0, 1.0))
    rng = np.random.default_rng(seed)
    ref, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=8, length=99, error_rate=0.06, rng=rng,
        ref_error_rate=0.1, ref_errors=ErrorModel(8.0, 0.0, 0.0, 1.0, 1.0),
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    kw = dict(_EQ_KW, seed_indels=False, indel_correction_only=icorr,
              ref_scores=REF_SCORES)
    base = rifraf(seqs, phreds=phreds, reference=ref,
                  params=RifrafParams(device_loop="off", **kw))
    dev = rifraf(seqs, phreds=phreds, reference=ref,
                 params=RifrafParams(device_loop="on", **kw))
    assert np.array_equal(base.consensus, dev.consensus)
    assert np.isclose(base.state.score, dev.state.score, rtol=1e-12)
    assert base.state.stage_iterations.tolist() == \
        dev.state.stage_iterations.tolist()
    for a, b in zip(base.consensus_stages, dev.consensus_stages):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            assert np.array_equal(x, y)
