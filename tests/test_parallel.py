"""Multi-device sharding tests on the 8-device virtual CPU mesh.

Validates the TP-like read sharding: per-read scores computed on separate
devices, reduced by XLA collectives, agreeing exactly with the single-device
path.
"""

import jax
import numpy as np
import pytest

from rifraf_tpu.engine.proposals import Deletion, Insertion, Substitution
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax
from rifraf_tpu.ops.proposal_jax import encode_proposals, score_proposals_batch
from rifraf_tpu.parallel.sharding import (
    make_mesh,
    pad_batch_to,
    shard_batch,
    sharded_consensus_step,
)

SCORES = Scores.from_error_model(ErrorModel(1.0, 5.0, 5.0))


def _problem(n_reads, tlen=24, seed=3):
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(18, 30))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -0.5, size=slen)
        reads.append(make_read_scores(s, log_p, 6, SCORES))
    return template, batch_reads(reads, dtype=np.float64)


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.slow
def test_sharded_step_matches_single_device():
    template, batch = _problem(n_reads=8)
    tlen = len(template)
    K = align_jax.band_height(batch, tlen)
    geom = align_jax.batch_geometry(batch, tlen)
    proposals = [
        Substitution(0, 1),
        Insertion(0, 2),
        Deletion(1),
        Substitution(tlen - 1, 0),
        Insertion(tlen, 3),
        Deletion(tlen - 1),
    ]

    # single-device reference
    A, _, scores, _ = align_jax.forward_batch(template, batch, tlen=tlen, K=K)
    B, _, _ = align_jax.backward_batch(template, batch, tlen=tlen, K=K)
    want_total = float(np.sum(scores))
    want_p = np.asarray(
        score_proposals_batch(A, B, batch, geom, proposals)
    ).sum(axis=0)

    # sharded across 8 devices
    mesh = make_mesh(8)
    sbatch = shard_batch(batch, mesh)
    weights = np.ones(8)
    total, ptotals = sharded_consensus_step(
        mesh, template, sbatch, geom, encode_proposals(proposals), weights, K
    )
    np.testing.assert_allclose(float(total), want_total, rtol=1e-12)
    np.testing.assert_allclose(np.asarray(ptotals), want_p, rtol=1e-12)


def test_padded_batch_weights_mask_dummies():
    template, batch = _problem(n_reads=5)
    tlen = len(template)
    padded, weights = pad_batch_to(batch, 8)
    assert padded.n_reads == 8
    assert weights.sum() == 5
    K = align_jax.band_height(padded, tlen)
    geom = align_jax.batch_geometry(padded, tlen)
    mesh = make_mesh(8)
    sbatch = shard_batch(padded, mesh)
    proposals = [Substitution(0, 1)]
    total, _ = sharded_consensus_step(
        mesh, template, sbatch, geom, encode_proposals(proposals), weights, K
    )
    # reference: unpadded single-device total
    _, _, scores, _ = align_jax.forward_batch(template, batch, tlen=tlen)
    np.testing.assert_allclose(float(total), float(np.sum(scores)), rtol=1e-12)


@pytest.mark.slow
def test_graft_entry_single_chip():
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import __graft_entry__ as ge

    fn, example_args = ge.entry()
    out = jax.jit(fn)(*example_args)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_graft_entry_dryrun_multichip():
    """Compiles the large sharded executables with the compilation cache
    disabled (a cache-serializer segfault workaround, __graft_entry__.py),
    so it dominates suite wall time — marked slow; CI runs it in its own
    job, `-m "not slow"` skips it locally."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_weighted_read_sum_masks_padding_not_neg_inf():
    """Padding rows (weight 0) contribute exactly 0 even with -inf/nan
    values; a real read's -inf proposal score must survive the reduction
    so impossible proposals rank below valid ones."""
    import jax.numpy as jnp

    from rifraf_tpu.parallel.sharding import weighted_read_sum

    weights = jnp.array([1.0, 1.0, 0.0])
    pscores = jnp.array(
        [
            [-1.0, -jnp.inf],
            [-2.0, -3.0],
            [jnp.nan, -jnp.inf],  # padding junk must not leak
        ]
    )
    out = np.asarray(weighted_read_sum(weights, pscores))
    assert out[0] == -3.0
    assert out[1] == -np.inf

    scores = jnp.array([-5.0, -7.0, jnp.nan])
    total = float(weighted_read_sum(weights, scores))
    assert total == -12.0


@pytest.mark.slow
def test_sharded_rifraf_matches_single_device():
    """The integrated mesh path: rifraf() with params.mesh sharding the
    read axis over the 8-device virtual mesh must return the identical
    consensus (and matching score) to the single-device run."""
    from rifraf_tpu.engine.driver import rifraf
    from rifraf_tpu.engine.params import RifrafParams
    from rifraf_tpu.models.errormodel import ErrorModel
    from rifraf_tpu.sim.sample import sample_sequences

    rng = np.random.default_rng(21)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=6, length=60, error_rate=0.02, rng=rng,
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )

    base = rifraf(seqs, phreds=phreds, params=RifrafParams())
    mesh = make_mesh(8)
    sharded = rifraf(seqs, phreds=phreds, params=RifrafParams(mesh=mesh))

    assert np.array_equal(base.consensus, sharded.consensus)
    assert np.array_equal(base.consensus, template)
    assert np.isclose(base.state.score, sharded.state.score)


@pytest.mark.slow
def test_sharded_rifraf_uneven_reads():
    """Read count not divisible by the mesh: padding via duplicated
    weight-0 reads must not change the answer."""
    from rifraf_tpu.engine.driver import rifraf
    from rifraf_tpu.engine.params import RifrafParams
    from rifraf_tpu.models.errormodel import ErrorModel
    from rifraf_tpu.sim.sample import sample_sequences

    rng = np.random.default_rng(33)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=5, length=48, error_rate=0.02, rng=rng,
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    base = rifraf(seqs, phreds=phreds, params=RifrafParams())
    mesh = make_mesh(8)  # 5 reads over 8 devices -> 3 padding rows
    sharded = rifraf(seqs, phreds=phreds, params=RifrafParams(mesh=mesh))
    assert np.array_equal(base.consensus, sharded.consensus)
    assert np.isclose(base.state.score, sharded.state.score)
