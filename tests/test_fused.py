"""Fused-step invariants: the packed output must be identical whether the
read axis runs all at once or in sequential memory-bounding chunks (incl.
a chunk size that does NOT divide the read count — the padding path)."""

import numpy as np
import pytest

# every test compiles the big fused XLA step (x64 CPU compile dominates on 1-core hosts)
pytestmark = pytest.mark.slow

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax
from rifraf_tpu.ops.fused import fused_step_full, pack_layout

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0))


def _problem(n_reads=7, tlen=48, seed=3):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(tlen - 5, tlen + 6))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -0.5, size=slen)
        reads.append(make_read_scores(s, log_p, 8, SCORES))
    batch = batch_reads(reads, dtype=np.float64)
    K = ((align_jax.band_height(batch, tlen) + 7) // 8) * 8
    geom = align_jax.batch_geometry(batch, tlen)
    t = jnp.asarray(np.pad(template, (0, 8)), jnp.int8)
    w = jnp.asarray(rng.uniform(0.5, 2.0, n_reads))
    args = (t, jnp.asarray(batch.seq), jnp.asarray(batch.match),
            jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
            jnp.asarray(batch.dels), geom, w)
    return args, K, n_reads, t.shape[0] + 1


@pytest.mark.parametrize("want_stats", [False, True])
@pytest.mark.parametrize("chunk", [3, 4, 7])  # 3/4 do not divide N=7
def test_chunked_fused_matches_unchunked(chunk, want_stats):
    args, K, N, T1 = _problem()
    A, B, _, packed_ref = fused_step_full(*args, K, False, want_stats)
    assert A is not None and B is not None
    A2, B2, _, packed_chk = fused_step_full(
        *args, K, False, want_stats, chunk
    )
    if chunk < N:
        assert A2 is None and B2 is None
    lay = pack_layout(N, T1, want_stats)
    ref = np.asarray(packed_ref)
    chk = np.asarray(packed_chk)
    assert ref.shape == chk.shape
    for name, (a, b) in lay.items():
        np.testing.assert_allclose(
            chk[a:b], ref[a:b], rtol=1e-12, atol=1e-12,
            err_msg=f"packed section {name!r} differs under chunking",
        )


def test_chunked_fused_moves_roundtrip():
    """want_moves with chunking returns the full, unpadded move band."""
    args, K, N, T1 = _problem()
    _, _, moves_ref, _ = fused_step_full(*args, K, True, False)
    _, _, moves_chk, _ = fused_step_full(*args, K, True, False, 3)
    np.testing.assert_array_equal(
        np.asarray(moves_chk), np.asarray(moves_ref)
    )


@pytest.mark.parametrize("tlen", [
    53,  # padded T = 61: unroll C = 1 (odd ad-hoc length)
    56,  # padded T = 64: unroll C = 16 — the production block path
])
def test_fwd_bwd_merged_matches_separate(tlen):
    """The single-scan fwd+bwd kernel must reproduce _forward_one and
    _backward_one exactly (bands, moves, scores) — at both the C=1 and
    the production C=16 unrolled-block scan paths."""
    import jax

    args, K, N, T1 = _problem(n_reads=5, tlen=tlen, seed=9)
    t, seq, match, mismatch, ins, dels, geom, _ = args
    fwd = jax.vmap(align_jax._forward_one,
                   in_axes=(None, 0, 0, 0, 0, 0, 0, None, None))
    bwd = jax.vmap(align_jax._backward_one,
                   in_axes=(None, 0, 0, 0, 0, 0, 0, None))
    A_ref, mv_ref, sc_ref = fwd(t, seq, match, mismatch, ins, dels, geom,
                                K, True)
    B_ref, _ = bwd(t, seq, match, mismatch, ins, dels, geom, K)
    merged = jax.vmap(align_jax._fwd_bwd_one,
                      in_axes=(None, 0, 0, 0, 0, 0, 0, None, None))
    A, mv, sc, B = merged(t, seq, match, mismatch, ins, dels, geom, K, True)
    np.testing.assert_array_equal(np.asarray(A), np.asarray(A_ref))
    np.testing.assert_array_equal(np.asarray(B), np.asarray(B_ref))
    np.testing.assert_array_equal(np.asarray(mv), np.asarray(mv_ref))
    np.testing.assert_array_equal(np.asarray(sc), np.asarray(sc_ref))


def test_driver_equal_under_forced_chunking(monkeypatch):
    """rifraf() must produce the identical consensus when the fused step
    is forced to run the read axis in sequential chunks (the big-problem
    memory path, exercised here at small scale via a tiny budget)."""
    from rifraf_tpu.engine import realign
    from rifraf_tpu.engine.driver import rifraf
    from rifraf_tpu.engine.params import RifrafParams
    from rifraf_tpu.models.errormodel import ErrorModel
    from rifraf_tpu.sim.sample import sample_sequences

    rng = np.random.default_rng(23)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=7, length=60, error_rate=0.02, rng=rng,
        seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
    )
    params = RifrafParams(batch_size=0, batch_fixed=False)
    base = rifraf(seqs, phreds=phreds, params=params)

    # the budget resolves per BatchAligner from the env override
    # (engine.realign._default_hbm_budget); teardown restores the env
    monkeypatch.setenv("RIFRAF_TPU_HBM_BUDGET", "1")  # force chunks
    chunked = rifraf(seqs, phreds=phreds, params=params)

    np.testing.assert_array_equal(base.consensus, chunked.consensus)
    assert base.state.converged == chunked.state.converged
