"""Dense all-edits scorer vs the per-proposal JAX scorer and the numpy
oracle: identical scores for every edit at every position."""

import numpy as np

from rifraf_tpu.engine.proposals import Deletion, Insertion, Substitution
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax
from rifraf_tpu.ops.proposal_dense import score_all_edits
from rifraf_tpu.ops.proposal_jax import score_proposals_batch

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0))


def _problem(n_reads=6, tlen=31, seed=7):
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(tlen - 6, tlen + 7))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -0.5, size=slen)
        reads.append(make_read_scores(s, log_p, 6, SCORES))
    return template, batch_reads(reads, dtype=np.float64)


def _all_edits(tlen):
    return (
        [Substitution(p, b) for p in range(tlen) for b in range(4)]
        + [Insertion(p, b) for p in range(tlen + 1) for b in range(4)]
        + [Deletion(p) for p in range(tlen)]
    )


def test_dense_matches_per_proposal_scorer():
    template, batch = _problem()
    tlen = len(template)
    K = align_jax.band_height(batch, tlen)
    A, _, _, geom = align_jax.forward_batch(template, batch, tlen=tlen, K=K)
    B, _, _ = align_jax.backward_batch(template, batch, tlen=tlen, K=K)

    sub_t, ins_t, del_t = score_all_edits(A, B, batch, geom)
    sub_t, ins_t, del_t = map(np.asarray, (sub_t, ins_t, del_t))

    proposals = _all_edits(tlen)
    want = np.asarray(
        score_proposals_batch(A, B, batch, geom, proposals)
    ).sum(axis=0)

    got = np.empty(len(proposals))
    for k, p in enumerate(proposals):
        if isinstance(p, Substitution):
            got[k] = sub_t[p.pos, p.base]
        elif isinstance(p, Insertion):
            got[k] = ins_t[p.pos, p.base]
        else:
            got[k] = del_t[p.pos]
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-12)


def test_dense_matches_full_realignment_oracle():
    """The exactness property (test_model.jl:39-153): a dense-table entry
    equals the full realignment score of the edited template."""
    from rifraf_tpu.engine.proposals import apply_proposals

    template, batch = _problem(n_reads=3, tlen=19, seed=11)
    tlen = len(template)
    K = align_jax.band_height(batch, tlen)
    A, _, _, geom = align_jax.forward_batch(template, batch, tlen=tlen, K=K)
    B, _, _ = align_jax.backward_batch(template, batch, tlen=tlen, K=K)
    sub_t, ins_t, del_t = map(
        np.asarray, score_all_edits(A, B, batch, geom)
    )

    rng = np.random.default_rng(0)
    cases = [Substitution(int(rng.integers(tlen)), int(rng.integers(4)))
             for _ in range(8)]
    cases += [Insertion(int(rng.integers(tlen + 1)), int(rng.integers(4)))
              for _ in range(8)]
    cases += [Deletion(int(rng.integers(tlen))) for _ in range(8)]
    cases += [Insertion(0, 2), Insertion(tlen, 1), Deletion(0),
              Deletion(tlen - 1), Substitution(0, 3),
              Substitution(tlen - 1, 0)]

    for p in cases:
        new_t = apply_proposals(template, [p])
        _, _, scores, _ = align_jax.forward_batch(
            new_t, batch, tlen=len(new_t), K=K + 2
        )
        want = float(np.sum(np.asarray(scores)))
        if isinstance(p, Substitution):
            got = sub_t[p.pos, p.base]
        elif isinstance(p, Insertion):
            got = ins_t[p.pos, p.base]
        else:
            got = del_t[p.pos]
        np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-10,
                                   err_msg=str(p))


def test_dense_weighted_masking():
    """Weight-0 rows contribute nothing even when their tables hold -inf."""
    template, batch = _problem(n_reads=4, tlen=23, seed=3)
    tlen = len(template)
    K = align_jax.band_height(batch, tlen)
    A, _, _, geom = align_jax.forward_batch(template, batch, tlen=tlen, K=K)
    B, _, _ = align_jax.backward_batch(template, batch, tlen=tlen, K=K)

    w_all = np.ones(4)
    w_masked = np.array([1.0, 1.0, 0.0, 0.0])
    full = map(np.asarray, score_all_edits(A, B, batch, geom, weights=w_all))
    part = map(np.asarray, score_all_edits(A, B, batch, geom, weights=w_masked))
    per_read = np.asarray(
        score_proposals_batch(A, B, batch, geom, _all_edits(tlen))
    )
    want_part = per_read[:2].sum(axis=0)
    sub_p, ins_p, del_p = part
    got = []
    for k, p in enumerate(_all_edits(tlen)):
        if isinstance(p, Substitution):
            got.append(sub_p[p.pos, p.base])
        elif isinstance(p, Insertion):
            got.append(ins_p[p.pos, p.base])
        else:
            got.append(del_p[p.pos])
    np.testing.assert_allclose(np.asarray(got), want_part, rtol=1e-12)


def test_blocked_dense_matches_unblocked():
    """dense_tables_blocked == the all-at-once dense sweep on identical
    inputs (the long-template memory path must be value-identical)."""
    import jax

    # the jax persistent-cache serializer segfaults writing some large
    # executables on this image (same workaround as __graft_entry__.py);
    # the blocked sweep's executable triggers it under x64 — skip cache
    # writes for this test only
    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        _run_blocked_dense_check()
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)


def _run_blocked_dense_check():
    import jax.numpy as jnp

    from rifraf_tpu.ops.proposal_dense import (
        _dense_batch,
        dense_tables_blocked,
    )

    template, batch = _problem(n_reads=5, tlen=90, seed=17)
    tlen = len(template)
    K = align_jax.band_height(batch, tlen)
    A, _, _, geom = align_jax.forward_batch(template, batch, tlen=tlen, K=K)
    B, _, _ = align_jax.backward_batch(template, batch, tlen=tlen, K=K)
    w = jnp.asarray(np.array([1.0, 0.0, 2.0, 1.0, 1.0]))  # incl. zero weight

    args = (jnp.asarray(batch.seq), jnp.asarray(batch.match),
            jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
            jnp.asarray(batch.dels))
    subs, insr, dele = _dense_batch(A, B, *args, geom)

    def wsum(x):
        wv = np.asarray(w).reshape((-1,) + (1,) * (x.ndim - 1))
        return np.sum(np.where(wv > 0, np.asarray(x), 0.0) * wv, axis=0)

    # valid ranges: substitutions/deletions at pos < tlen, insertions at
    # pos <= tlen; entries beyond are garbage by contract in BOTH paths
    for block in (16, 64, 128):
        sb, ib, db = dense_tables_blocked(A, B, *args, geom, w, block=block)
        np.testing.assert_allclose(np.asarray(sb)[:tlen], wsum(subs)[:tlen],
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(np.asarray(ib)[:tlen + 1],
                                   wsum(insr)[:tlen + 1],
                                   rtol=1e-12, atol=1e-12)
        np.testing.assert_allclose(np.asarray(db)[:tlen], wsum(dele)[:tlen],
                                   rtol=1e-12, atol=1e-12)
