"""Elastic serving: queue-driven autoscaling with graceful drain,
deadline-aware load shedding, and the supervisor's backoff reset.

Everything here runs the fallback path (batch_max_reads=1 — no
batch-grid compiles) so the suite exercises the fleet lifecycle, not
device compilation. The drain/close race and the no-hung-futures
invariant get explicit regression tests; bit-identity of an elastic
fleet against the fixed single-worker server rides the scale-up test.
"""

import threading
import time

import numpy as np
import pytest

from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.serve import (
    ConsensusServer,
    ServeConfig,
    SheddedError,
)
from rifraf_tpu.serve.worker import Worker
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _cluster(nseqs=3, length=30, seed=0):
    rng = np.random.default_rng(seed)
    params = RifrafParams()
    _, _, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=nseqs, length=length, error_rate=0.02, rng=rng,
        seq_errors=SEQ_ERRORS,
    )
    return [
        make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                         params.bandwidth, params.scores)
        for s, p in zip(seqs, phreds)
    ]


def _serve_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("rifraf-serve")]


def _elastic_cfg(**kw):
    """Fallback-path elastic config: fast supervisor, tight scaling
    thresholds so a handful of requests triggers growth and a short
    idle triggers drain."""
    kw.setdefault("batch_max_reads", 1)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("supervise_interval_s", 0.02)
    kw.setdefault("min_workers", 1)
    kw.setdefault("max_workers", 3)
    kw.setdefault("scale_up_depth", 1)
    kw.setdefault("scale_cooldown_s", 0.02)
    kw.setdefault("scale_down_idle_s", 0.2)
    return ServeConfig(**kw)


def _wait_for(predicate, timeout_s=30.0, poll_s=0.02):
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if predicate():
            return True
        time.sleep(poll_s)
    return predicate()


# ------------------------------------------------------- config guards


def test_elastic_config_validation():
    with pytest.raises(ValueError, match="max_workers"):
        ConsensusServer(_elastic_cfg(min_workers=4, max_workers=2),
                        start=False)


def test_elastic_initial_size_clamped():
    srv = ConsensusServer(
        _elastic_cfg(n_workers=8, min_workers=1, max_workers=2),
        start=False)
    try:
        assert len(srv._workers) == 2
    finally:
        srv.close()
    srv = ConsensusServer(
        _elastic_cfg(n_workers=1, min_workers=2, max_workers=4),
        start=False)
    try:
        assert len(srv._workers) == 2
    finally:
        srv.close()


# ------------------------------------- scale up, drain down, identity


def test_scale_up_then_drain_down_bit_identical():
    """Queue pressure grows the fleet, sustained idleness drains it
    back to min_workers (graceful: every future resolves ok), and the
    elastic results equal the fixed single-worker reference
    bit-for-bit."""
    clusters = [_cluster(seed=i) for i in range(6)]
    srv = ConsensusServer(_elastic_cfg())
    try:
        futs = [srv.submit(c) for c in clusters]
        res = [f.result(timeout=120) for f in futs]
        assert all(r.ok for r in res)
        h = srv.health()
        assert h["elastic"]["scale_up_events"] >= 1
        assert h["elastic"]["max_workers"] == 3

        # drain back down: active returns to min_workers, drained slots
        # retire (their threads exit on their own), nothing requeues
        assert _wait_for(lambda: (
            srv.health()["elastic"]["active_workers"] == 1
            and not srv.health()["elastic"]["draining"]
        ), timeout_s=30)
        h = srv.health()
        assert h["elastic"]["scale_down_events"] >= 1
        assert h["elastic"]["retired"]
        assert h["outstanding"] == 0
        # a retired slot is not a dead worker: the fleet is healthy
        assert h["healthy"] and h["worker_alive"]
        assert h["worker_restarts"] == 0
    finally:
        srv.close()
    assert not any(t.is_alive() for t in _serve_threads())

    # bit-identity against the fixed single-worker configuration
    ref = ConsensusServer(_elastic_cfg(min_workers=0, max_workers=0,
                                       n_workers=1))
    try:
        ref_res = [ref.submit(c).result(timeout=120) for c in clusters]
    finally:
        ref.close()
    for a, b in zip(res, ref_res):
        assert b.ok
        assert np.array_equal(np.asarray(a.consensus),
                              np.asarray(b.consensus))
        assert a.score == b.score


def test_scale_up_reuses_retired_slot():
    """A drained slot's index is recycled by the next scale-up instead
    of growing the worker list without bound. Driven through the
    scaling primitives directly — the organic path is covered by
    test_scale_up_then_drain_down_bit_identical."""
    srv = ConsensusServer(_elastic_cfg(max_workers=2,
                                       scale_down_idle_s=60.0))
    try:
        srv._scale_up()
        assert sorted(srv._active_slots()) == [0, 1]
        srv._scale_down(1)
        assert _wait_for(
            lambda: srv.health()["elastic"]["retired"] == [1],
            timeout_s=30)
        srv._scale_up()
        assert sorted(srv._active_slots()) == [0, 1]
        h = srv.health()
        assert h["elastic"]["retired"] == []
        assert h["elastic"]["scale_up_events"] == 2
        assert len(srv._workers) == 2  # slot 1 was reused, not appended
        # the recycled fleet still serves
        assert srv.submit(_cluster(seed=42)).result(timeout=120).ok
    finally:
        srv.close()
    assert not any(t.is_alive() for t in _serve_threads())


# --------------------------------------------- drain vs close() race


def test_close_racing_drain_resolves_everything_once():
    """close() arriving while a scale-down drain is in flight must not
    double-resolve or leak futures: every submitted future resolves
    exactly once, every thread exits, and the STOP sentinels only go to
    slots that still have a consumer."""
    srv = ConsensusServer(_elastic_cfg(scale_down_idle_s=30.0))
    try:
        futs = [srv.submit(_cluster(seed=i)) for i in range(4)]
        for f in futs:
            assert f.result(timeout=120).ok
        # force a drain by hand (idle threshold is out of reach) and
        # close immediately, racing the worker's drain-exit against the
        # shutdown's STOP fan-out
        if len(srv._active_slots()) < 2:
            srv._scale_up()
        active = srv._active_slots()
        assert len(active) >= 2
        srv._scale_down(max(active))
    finally:
        srv.close()
    assert not any(t.is_alive() for t in _serve_threads())
    assert all(f.done() for f in futs)
    h = srv.health()
    assert h["outstanding"] == 0
    # exactly one resolution per future: any double-resolve attempt
    # would have been counted by resolve_future
    assert srv.stats.get("double_resolve") == 0


def test_drained_worker_requeues_nothing():
    """A draining worker finishes its burst and exits without touching
    the shared queue: queued flushes stay for the rest of the fleet."""
    srv = ConsensusServer(_elastic_cfg(max_workers=2,
                                       scale_down_idle_s=30.0))
    try:
        futs = [srv.submit(_cluster(seed=i)) for i in range(4)]
        for f in futs:
            assert f.result(timeout=120).ok
        active = srv._active_slots()
        if len(active) > 1:
            srv._scale_down(max(active))
            assert _wait_for(
                lambda: srv.health()["elastic"]["retired"],
                timeout_s=30)
        # the drained slot exited cleanly (no crash recovery ran)
        assert srv.stats.get("worker_crashes") == 0
        assert srv.stats.get("ladder_retry_fallback") == 0
        # remaining capacity still serves
        assert srv.submit(_cluster(seed=99)).result(timeout=120).ok
    finally:
        srv.close()
    assert not any(t.is_alive() for t in _serve_threads())


# ---------------------------------- parked slots vs the elastic target


def test_parked_slots_excluded_and_scale_up_parks_on_probe_failure(
        monkeypatch):
    """A parked (probe-failed) slot is NOT elastic capacity: the
    supervisor recruits a replacement, and a recruit that also fails
    the golden probe parks instead of restart-looping — bounded by
    max_workers, with zero restart budget spent on recruits."""
    probe_ok = {"ok": False}

    def fake_probe(self):
        self._last_probe = time.perf_counter()
        ok = probe_ok["ok"]
        self.stats.count("probe_pass" if ok else "probe_fail")
        if self.scoreboard is not None:
            was = self.scoreboard.is_quarantined(self.device)
            self.scoreboard.note_probe(self.device, ok)
            if ok and was:
                self.stats.count("device_reinstated")
        return ok

    monkeypatch.setattr(Worker, "golden_probe", fake_probe)
    srv = ConsensusServer(_elastic_cfg(
        guard=True, probe_interval_s=0.01, max_workers=2,
        faults="fallback:crash:n=1"))
    try:
        fut = srv.submit(_cluster())
        # the injected crash parks slot 0; the supervisor, seeing zero
        # active workers (< min), recruits slot 1 — whose probe also
        # fails, so it parks too. Fleet growth stops at max_workers.
        assert _wait_for(lambda: (
            srv.health()["integrity"]["parked_workers"] == [0, 1]
        ), timeout_s=30)
        h = srv.health()
        assert h["elastic"]["active_workers"] == 0
        assert len(srv._workers) == 2  # bounded: no parked-slot minting
        assert h["worker_restarts"] == 1  # the crash; recruits are free
        assert not fut.done()  # requeued work waits for a clean probe
        probe_ok["ok"] = True
        assert fut.result(timeout=120).ok
        assert _wait_for(lambda: (
            srv.health()["integrity"]["parked_workers"] == []
        ), timeout_s=30)
    finally:
        srv.close()
    assert not any(t.is_alive() for t in _serve_threads())


# ------------------------------------------------- backoff forgiveness


def test_restart_backoff_resets_after_sustained_health():
    """A crash after restart_backoff_reset_s of clean running forgives
    the restart history; a crash inside the window does not."""
    srv = ConsensusServer(
        _elastic_cfg(restart_backoff_reset_s=0.05), start=False)
    try:
        srv._worker_restarts = 3
        srv._batcher_restarts = 1
        srv._last_crash = time.perf_counter()  # crash just happened
        srv._note_crash()  # inside the window: history stands
        assert srv._worker_restarts == 3
        assert srv.stats.get("backoff_resets") == 0
        srv._last_crash = time.perf_counter() - 1.0  # sustained health
        srv._note_crash()
        assert srv._worker_restarts == 0
        assert srv._batcher_restarts == 0
        assert srv.stats.get("backoff_resets") == 1
        assert srv.health()["elastic"]["backoff_resets"] == 1
    finally:
        srv.close()


def test_supervisor_applies_backoff_reset_on_real_crash():
    """End to end: one injected crash long after start (reset window
    tiny) both restarts the worker and logs a backoff reset."""
    srv = ConsensusServer(_elastic_cfg(
        min_workers=0, max_workers=0, n_workers=1,
        restart_backoff_reset_s=0.0,
        faults="fallback:crash:n=1"))
    try:
        srv._worker_restarts = 2  # pretend history from earlier crashes
        fut = srv.submit(_cluster())
        assert fut.result(timeout=120).ok
        assert srv.stats.get("backoff_resets") >= 1
        # the reset zeroed history BEFORE the restart was counted
        assert srv.health()["worker_restarts"] == 1
    finally:
        srv.close()
    assert not any(t.is_alive() for t in _serve_threads())


# ------------------------------------------------------- load shedding


def test_shed_typed_with_retry_after_hint():
    """Admission sheds a deadline the estimated queue already consumes:
    typed SheddedError, retry_after_s > 0, counted; deadline-free and
    generous-deadline requests are admitted."""
    srv = ConsensusServer(_elastic_cfg(shed=True), start=False)
    try:
        # seed the estimator: 10 s of service per request, one request
        # already outstanding, one active-at-most worker
        srv.stats.note_service(10.0)
        srv.submit(_cluster(seed=0))  # no deadline: never shed
        with pytest.raises(SheddedError) as ei:
            srv.submit(_cluster(seed=1), deadline_ms=100.0)
        assert ei.value.code == "shedded"
        assert ei.value.retry_after_s > 0
        assert srv.stats.get("shedded") == 1
        # a generous deadline clears the estimate and is admitted
        fut = srv.submit(_cluster(seed=2), deadline_ms=60_000.0)
        assert fut is not None
        h = srv.health()
        assert h["shed"]["enabled"]
        assert h["shed"]["shedded"] == 1
        assert h["shed"]["estimated_wait_s"] > 0
    finally:
        srv.close()


def test_shed_disabled_and_unseeded_admit_everything():
    """shed=False (the default) never sheds; shed=True with no service
    observations admits everything (no evidence, no refusals)."""
    srv = ConsensusServer(_elastic_cfg(shed=False), start=False)
    try:
        srv.stats.note_service(10.0)
        srv.submit(_cluster(seed=0))
        srv.submit(_cluster(seed=1), deadline_ms=1.0)  # not shed
        assert srv.stats.get("shedded") == 0
        assert "shed" not in srv.health()
    finally:
        srv.close()
    srv = ConsensusServer(_elastic_cfg(shed=True), start=False)
    try:
        srv.submit(_cluster(seed=0), deadline_ms=1.0)  # estimator empty
        assert srv.stats.get("shedded") == 0
    finally:
        srv.close()


def test_shed_under_synthetic_overload_keeps_admitted_available():
    """Under a queue the server cannot clear in time, every rejection
    is a typed SheddedError and every ADMITTED request still resolves
    (ok or typed) — availability of the admitted set, no hung
    futures."""
    srv = ConsensusServer(_elastic_cfg(
        shed=True, min_workers=0, max_workers=0, n_workers=1))
    try:
        # one real request seeds the service EWMA
        assert srv.submit(_cluster(seed=0)).result(timeout=120).ok
        # inflate the estimator so tight deadlines shed deterministically
        srv.stats.note_service(30.0)
        admitted, shed = [], 0
        for i in range(8):
            try:
                admitted.append(
                    srv.submit(_cluster(seed=i), deadline_ms=50.0))
            except SheddedError:
                shed += 1
        assert shed >= 1
        assert srv.stats.get("shedded") == shed
        for f in admitted:
            f.result(timeout=120)  # resolves (ok or typed), never hangs
    finally:
        srv.close()
    assert not any(t.is_alive() for t in _serve_threads())
