"""BandedArray tests, ported (to 0-based indexing) from
/root/reference/test/test_bandedarrays.jl."""

import numpy as np
import pytest

from rifraf_tpu.ops.banded_array import BandedArray, equal_ranges


def test_inband():
    m = BandedArray((13, 11), 5, dtype=np.int64)
    assert m.inband(0, 0)
    assert m.inband(7, 0)
    assert not m.inband(8, 0)
    assert m.inband(0, 5)
    assert not m.inband(0, 6)


def test_data_row():
    m = BandedArray((3, 3), 1, dtype=np.int64)
    assert m.data_row(0, 0) == 1
    assert m.data_row(1, 0) == 2
    assert m.data_row(0, 1) == 0
    assert m.data_row(1, 1) == 1
    assert m.data_row(2, 1) == 2
    assert m.data_row(1, 2) == 0
    assert m.data_row(2, 2) == 1

    m = BandedArray((3, 5), 1, dtype=np.int64)
    assert m.data_row(0, 0) == 3
    assert m.data_row(2, 4) == 1

    m = BandedArray((5, 3), 1, dtype=np.int64)
    assert m.data_row(0, 0) == 1
    assert m.data_row(4, 2) == 3


def test_row_range():
    m = BandedArray((3, 5), 1, dtype=np.int64)
    assert m.row_range(0) == (0, 1)
    assert m.row_range(1) == (0, 2)

    m = BandedArray((5, 3), 1, dtype=np.int64)
    assert m.row_range(0) == (0, 3)
    assert m.row_range(1) == (0, 4)


def test_data_row_range():
    m = BandedArray((3, 5), 1, dtype=np.int64)
    assert m.data_row_range(0) == (3, 4)
    assert m.data_row_range(1) == (2, 4)

    m = BandedArray((5, 3), 1, dtype=np.int64)
    assert m.data_row_range(0) == (1, 4)
    assert m.data_row_range(1) == (0, 4)


def test_sparsecol():
    m = BandedArray((5, 3), 1, dtype=np.int64)
    m[0, 0] = 1
    np.testing.assert_array_equal(m.sparsecol(0), [1, 0, 0, 0])


def test_flip():
    m = BandedArray((5, 3), 1, dtype=np.int64)
    m[0, 0] = 1
    m.flip()
    assert m[4, 2] == 1


def test_sym_band():
    m = BandedArray((3, 3), 1, dtype=np.int64)
    m.data[:] = 1
    expected = np.ones((3, 3), dtype=np.int64)
    expected[2, 0] = 0
    expected[0, 2] = 0
    np.testing.assert_array_equal(m.full(), expected)


def test_wide():
    m = BandedArray((3, 4), 1, dtype=np.int64)
    m.data[:] = 1
    expected = np.ones((3, 4), dtype=np.int64)
    expected[0, -1] = 0
    expected[-1, 0] = 0
    np.testing.assert_array_equal(m.full(), expected)


def test_wide_col():
    m = BandedArray((3, 5), 1, dtype=np.int64)
    m.data[:] = 1
    np.testing.assert_array_equal(m.sparsecol(0), [1, 1])
    for j in (1, 2, 3):
        np.testing.assert_array_equal(m.sparsecol(j), [1, 1, 1])
    np.testing.assert_array_equal(m.sparsecol(4), [1, 1])


def test_tall():
    m = BandedArray((4, 3), 1, dtype=np.int64)
    m.data[:] = 1
    expected = np.ones((4, 3), dtype=np.int64)
    expected[0, -1] = 0
    expected[-1, 0] = 0
    np.testing.assert_array_equal(m.full(), expected)


def test_tall_band():
    m = BandedArray((5, 3), 1, dtype=np.int64)
    m.data[:] = 1
    expected = np.ones((5, 3), dtype=np.int64)
    expected[4, 0] = 0
    expected[0, 2] = 0
    np.testing.assert_array_equal(m.full(), expected)


def test_individual_setting():
    m = BandedArray((3, 3), 1, dtype=np.int64)
    m[0, 1] = 3
    m[1, 0] = 5
    expected = np.zeros((3, 3), dtype=np.int64)
    expected[0, 1] = 3
    expected[1, 0] = 5
    np.testing.assert_array_equal(m.full(), expected)


def test_set_entire_band():
    m = BandedArray((3, 3), 1, dtype=np.int64)
    for (i, j, v) in [(0, 0, 1), (1, 0, 1), (0, 1, 2), (1, 1, 2), (2, 1, 2), (1, 2, 3), (2, 2, 3)]:
        m[i, j] = v
    expected = np.zeros((3, 3), dtype=np.int64)
    expected[0:2, 0] = 1
    expected[0:3, 1] = 2
    expected[1:3, 2] = 3
    np.testing.assert_array_equal(m.full(), expected)


def test_out_of_band_get_set():
    m = BandedArray((13, 11), 5, default=-np.inf)
    m[0, 0] = 1.0
    assert m[0, 0] == 1.0
    assert m[12, 0] == -np.inf
    with pytest.raises(IndexError):
        m[12, 0] = 1.0


def test_resize():
    m = BandedArray((5, 5), 1, dtype=np.int64)
    old = m.data
    m.resize((3, 3))
    assert m.data is old  # resize down reuses storage
    m.resize((5, 10))
    assert m.data is not old
    assert m.row_range(0) == (0, 1)
    assert m.row_range(2) == (0, 3)
    assert m.row_range(4) == (0, 4)
    assert m.row_range(9) == (3, 4)


def test_equal_ranges():
    # 0-based inclusive row ranges; returns half-open index ranges
    assert equal_ranges((2, 4), (3, 5)) == ((1, 3), (0, 2))
    assert equal_ranges((0, 4), (0, 1)) == ((0, 2), (0, 2))
    assert equal_ranges((0, 4), (3, 4)) == ((3, 5), (0, 2))
