"""Sweep-scheduler planner invariants (parallel.sweep_sharded.plan_sweep)
and the host/device pipeline helper (parallel.cluster.pipeline_map).

Pure host arithmetic — no device programs are built, so this runs in the
fast (non-slow) suite.
"""

import threading

import numpy as np
import pytest

from rifraf_tpu.parallel.cluster import pipeline_map
from rifraf_tpu.parallel.sweep_sharded import plan_cells, plan_sweep


class _Read:
    """The minimal read surface the planner touches."""

    def __init__(self, n, bandwidth=10, score=0.0):
        self.seq = np.zeros(n, np.int8)
        self.match_scores = np.full(n, score)
        self.bandwidth = bandwidth

    def __len__(self):
        return len(self.seq)


def _cluster(n_reads, length, bandwidth=10):
    # read 0 gets the best seed score so tlen0 == length, deterministically
    return [_Read(length, bandwidth, score=-float(k))
            for k in range(n_reads)]


HET = (
    [_cluster(4, 50), _cluster(9, 80), _cluster(5, 50), _cluster(8, 81),
     _cluster(4, 52), _cluster(12, 300), _cluster(3, 49), _cluster(4, 51),
     _cluster(5, 53), _cluster(4, 48)]
)


def test_plan_partitions_inputs_in_order():
    """Every input cluster lands in exactly one chunk, and chunks
    preserve input order within a bucket."""
    plans = plan_sweep(HET)
    seen = [i for p in plans for ch in p.chunks for i in ch]
    assert sorted(seen) == list(range(len(HET)))
    for p in plans:
        flat = [i for ch in p.chunks for i in ch]
        assert flat == sorted(flat)


def test_plan_keys_on_grid_and_cover_members():
    plans = plan_sweep(HET, read_bucket=8, band_bucket=16, len_bucket=64)
    for p in plans:
        n_pad, l_pad, t_max, k0 = p.key
        assert n_pad % 8 == 0 and l_pad % 64 == 0 and t_max % 64 == 0
        assert k0 % 16 == 0
        for ch in p.chunks:
            for i in ch:
                c = HET[i]
                assert len(c) <= n_pad
                assert max(len(r) for r in c) <= l_pad
                # tlen0 + 2 <= Tmax leaves insertion room for the seed
                assert len(c[0]) + 2 <= t_max


def test_plan_pinned_chunk_shapes():
    """cluster_chunk splits every bucket into chunks PADDED TO ONE gp —
    the executable-reuse fix: a tail chunk never gets its own shape."""
    plans = plan_sweep(HET, cluster_chunk=2, n_axis=1)
    assert sum(len(p.chunks) for p in plans) > len(plans)  # chunking happened
    for p in plans:
        for ch in p.chunks:
            assert 0 < len(ch) <= p.gp
    # the big bucket splits into multiple chunks that all share one gp
    big = max(plans, key=lambda p: sum(len(c) for c in p.chunks))
    assert len(big.chunks) > 1
    assert all(len(c) == big.gp for c in big.chunks[:-1])


def test_plan_gp_respects_mesh_axis():
    for n_axis in (1, 2, 3, 8):
        for p in plan_sweep(HET, n_axis=n_axis):
            assert p.gp % n_axis == 0
        for p in plan_sweep(HET, scheduler="uniform", n_axis=n_axis):
            assert p.gp % n_axis == 0


def test_uniform_is_single_global_bucket():
    plans = plan_sweep(HET, scheduler="uniform")
    assert len(plans) == 1
    p = plans[0]
    assert p.band == 8
    assert p.key[0] == max(len(c) for c in HET)  # raw read count
    assert p.key[1] == 320  # bucket(300, 64)
    assert len(p.chunks) == 1 and len(p.chunks[0]) == len(HET)


def test_bucketed_never_pads_more_than_uniform():
    """The point of the scheduler: heterogeneous inputs allocate fewer
    padded device cells bucketed than uniform."""
    bucketed = plan_cells(plan_sweep(HET))
    uniform = plan_cells(plan_sweep(HET, scheduler="uniform"))
    assert bucketed < uniform
    # homogeneous inputs: bucketing can't lose to within-grid rounding
    homog = [_cluster(8, 64) for _ in range(8)]
    assert plan_cells(plan_sweep(homog)) <= plan_cells(
        plan_sweep(homog, scheduler="uniform")
    )


def test_unknown_scheduler_rejected():
    with pytest.raises(ValueError):
        plan_sweep(HET, scheduler="magic")


def test_pipeline_map_order_and_overlap():
    """Results come back in item order; item k's collect happens only
    AFTER item k+1's dispatch (the double-buffer schedule)."""
    events = []
    lock = threading.Lock()

    def log(tag, x):
        with lock:
            events.append((tag, x))

    def pack(x):
        log("pack", x)
        return x * 10

    def run(p):
        log("run", p // 10)
        return p + 1

    def collect(h):
        log("collect", (h - 1) // 10)
        return h

    out = pipeline_map(pack, run, collect, [0, 1, 2, 3])
    assert out == [1, 11, 21, 31]
    order = {("run", i): k for k, (t, i) in enumerate(events) if t == "run"}
    for t, i in events:
        if t == "collect" and i + 1 < 4:
            assert order[("run", i + 1)] < events.index(("collect", i))


def test_pipeline_map_empty_and_single():
    assert pipeline_map(lambda x: x, lambda x: x, lambda x: x, []) == []
    assert pipeline_map(
        lambda x: x + 1, lambda x: x * 2, lambda x: x - 1, [5]
    ) == [11]


def test_pipeline_map_propagates_errors():
    def bad_run(p):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError):
        pipeline_map(lambda x: x, bad_run, lambda x: x, [1, 2])
