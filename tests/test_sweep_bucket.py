"""Sweep-scheduler planner invariants (parallel.sweep_sharded.plan_sweep)
and the host/device pipeline helper (parallel.cluster.pipeline_map).

Pure host arithmetic — no device programs are built, so this runs in the
fast (non-slow) suite.
"""

import threading

import numpy as np
import pytest

from rifraf_tpu.parallel.cluster import pipeline_map
from rifraf_tpu.parallel.sweep_sharded import (
    SegmentBucketPlan,
    plan_cells,
    plan_sweep,
)


def _chunk_members(p, ch):
    """Cluster indices of one chunk: plain for a BucketPlan, unpacked
    from the PackPlans of a segment-packed chunk."""
    if isinstance(p, SegmentBucketPlan):
        return [m[0] for pk in ch for m in pk.members]
    return list(ch)


class _Read:
    """The minimal read surface the planner touches."""

    def __init__(self, n, bandwidth=10, score=0.0):
        self.seq = np.zeros(n, np.int8)
        self.match_scores = np.full(n, score)
        self.bandwidth = bandwidth

    def __len__(self):
        return len(self.seq)


def _cluster(n_reads, length, bandwidth=10):
    # read 0 gets the best seed score so tlen0 == length, deterministically
    return [_Read(length, bandwidth, score=-float(k))
            for k in range(n_reads)]


HET = (
    [_cluster(4, 50), _cluster(9, 80), _cluster(5, 50), _cluster(8, 81),
     _cluster(4, 52), _cluster(12, 300), _cluster(3, 49), _cluster(4, 51),
     _cluster(5, 53), _cluster(4, 48)]
)


def test_plan_partitions_inputs_in_order():
    """Every input cluster lands in exactly one chunk, and chunks
    preserve input order within a bucket — whether the chunk holds
    whole-block members or segment-packed PackPlans."""
    plans = plan_sweep(HET)
    seen = [i for p in plans for ch in p.chunks
            for i in _chunk_members(p, ch)]
    assert sorted(seen) == list(range(len(HET)))
    for p in plans:
        flat = [i for ch in p.chunks for i in _chunk_members(p, ch)]
        assert flat == sorted(flat)


def test_plan_keys_on_grid_and_cover_members():
    plans = plan_sweep(HET, read_bucket=8, band_bucket=16, len_bucket=64)
    for p in plans:
        n_pad, l_pad, t_max, k0 = p.key
        assert n_pad % 8 == 0 and l_pad % 64 == 0 and t_max % 64 == 0
        assert k0 % 16 == 0
        for ch in p.chunks:
            for i in _chunk_members(p, ch):
                c = HET[i]
                assert len(c) <= n_pad
                assert max(len(r) for r in c) <= l_pad
                # tlen0 + 2 <= Tmax leaves insertion room for the seed
                assert len(c[0]) + 2 <= t_max


def test_plan_pinned_chunk_shapes():
    """cluster_chunk splits every bucket into chunks PADDED TO ONE gp —
    the executable-reuse fix: a tail chunk never gets its own shape.
    lane_target=0 isolates the invariant from the lane-packing floor
    (which would lift these small buckets to one full-tile chunk)."""
    plans = plan_sweep(HET, cluster_chunk=2, n_axis=1, lane_target=0)
    assert sum(len(p.chunks) for p in plans) > len(plans)  # chunking happened
    for p in plans:
        for ch in p.chunks:
            assert 0 < len(ch) <= p.gp
    # the big bucket splits into multiple chunks that all share one gp
    big = max(plans, key=lambda p: sum(len(c) for c in p.chunks))
    assert len(big.chunks) > 1
    assert all(len(c) == big.gp for c in big.chunks[:-1])


def test_plan_gp_respects_mesh_axis():
    for n_axis in (1, 2, 3, 8):
        for p in plan_sweep(HET, n_axis=n_axis):
            assert p.gp % n_axis == 0
        for p in plan_sweep(HET, scheduler="uniform", n_axis=n_axis):
            assert p.gp % n_axis == 0


def test_uniform_is_single_global_bucket():
    plans = plan_sweep(HET, scheduler="uniform")
    assert len(plans) == 1
    p = plans[0]
    assert p.band == 8
    assert p.key[0] == max(len(c) for c in HET)  # raw read count
    assert p.key[1] == 320  # bucket(300, 64)
    assert len(p.chunks) == 1 and len(p.chunks[0]) == len(HET)


def test_bucketed_never_pads_more_than_uniform():
    """The point of the scheduler: heterogeneous inputs allocate fewer
    padded device cells bucketed than uniform. lane_target=0 isolates
    the bucketing invariant from the lane-packing coalescer, which
    deliberately trades padded cells (reported as waste) for lane-tile
    fill and fewer launches on tile-underfilled buckets."""
    bucketed = plan_cells(plan_sweep(HET, lane_target=0))
    uniform = plan_cells(plan_sweep(HET, scheduler="uniform"))
    assert bucketed < uniform
    # homogeneous inputs: bucketing can't lose to within-grid rounding
    homog = [_cluster(8, 64) for _ in range(8)]
    assert plan_cells(plan_sweep(homog)) <= plan_cells(
        plan_sweep(homog, scheduler="uniform")
    )


def test_unknown_scheduler_rejected():
    with pytest.raises(ValueError):
        plan_sweep(HET, scheduler="magic")


def test_lane_target_fills_lane_tiles():
    """The lane-packing floor: with a small cluster_chunk, a bucket of
    small clusters (Npad=8) still packs ceil(128/8)=16 clusters per
    chunk (bounded by member count), so each launch fills the 128-lane
    axis instead of dispatching a quarter-full tile.

    segment_pack=False pins the WHOLE-BLOCK floor this test documents:
    with the default segment packing these clusters would instead share
    read-granularity lane blocks (tests/test_lane_packing.py covers
    that path)."""
    many = [_cluster(5, 50) for _ in range(40)]  # one bucket, Npad=8
    plans = plan_sweep(many, cluster_chunk=2, n_axis=1, lane_target=128,
                       segment_pack=False)
    assert len(plans) == 1
    p = plans[0]
    assert p.key[0] == 8
    assert p.gp == 16  # ceil(128 / 8), overriding cluster_chunk=2
    assert p.gp * p.key[0] >= 128
    # bounded by membership: 3 members can't be packed to 16
    few = [_cluster(5, 50) for _ in range(3)]
    (pf,) = plan_sweep(few, cluster_chunk=2, n_axis=1, lane_target=128,
                       segment_pack=False)
    assert pf.gp == 3


def test_lane_target_leaves_big_clusters_alone():
    """A bucket that already fills the lane axis (Npad >= lane_target)
    keeps its cluster_chunk-driven chunking."""
    big = [_cluster(120, 50) for _ in range(8)]  # Npad=120 -> bucket 120
    plans = plan_sweep(big, cluster_chunk=2, n_axis=1, lane_target=128)
    (p,) = plans
    assert p.gp == 2  # ceil(128/120)=2 == cluster_chunk — no inflation
    # uniform scheduler ignores the floor entirely (legacy layout)
    (pu,) = plan_sweep([_cluster(5, 50) for _ in range(40)],
                       scheduler="uniform", cluster_chunk=2, n_axis=1,
                       lane_target=128)
    assert pu.gp == 2


def test_lane_target_coalesces_underfilled_buckets():
    """Buckets whose whole membership cannot fill one 128-lane tile are
    merged into coarser-grid neighbours (and finally absorbed per
    read-count class), so a ragtag of near-miss shapes shares fuller
    launches instead of each paying a mostly-empty tile + a compile.

    segment_pack=False pins the WHOLE-BLOCK coalescer this test
    documents — the default segment packer supersedes it for clusters
    this small (tests/test_lane_packing.py covers that path)."""
    # 8 tiny clusters spread over 8 distinct fine length buckets
    ragtag = [_cluster(4, 40 + 70 * k) for k in range(8)]
    fine = plan_sweep(ragtag, lane_target=0)
    packed = plan_sweep(ragtag, lane_target=128, segment_pack=False)
    assert len(fine) == 8
    assert len(packed) < len(fine)
    # coverage: every cluster in exactly one chunk, members in input
    # order, and every merged key still covers its members' demands
    seen = sorted(i for p in packed for ch in p.chunks for i in ch)
    assert seen == list(range(len(ragtag)))
    for p in packed:
        flat = [i for ch in p.chunks for i in ch]
        assert flat == sorted(flat)
        for i in flat:
            c = ragtag[i]
            assert len(c) <= p.key[0]
            assert max(len(r) for r in c) <= p.key[1]
            assert len(c[0]) + 2 <= p.key[2]
    # read-count classes never merge: a 4-read cluster stays in an
    # Npad=8 bucket even after coalescing (coarsening Npad would pad
    # every cluster's read lanes — the waste packing exists to avoid)
    mixed = [_cluster(4, 40 + 30 * k) for k in range(4)] + [
        _cluster(12, 40 + 30 * k) for k in range(4)
    ]
    for p in plan_sweep(mixed, lane_target=128, segment_pack=False):
        npads = {8 if len(mixed[i]) <= 8 else 16
                 for ch in p.chunks for i in ch}
        assert npads == {p.key[0]}


def test_pipeline_map_order_and_overlap():
    """Results come back in item order; item k's collect happens only
    AFTER item k+1's dispatch (the double-buffer schedule)."""
    events = []
    lock = threading.Lock()

    def log(tag, x):
        with lock:
            events.append((tag, x))

    def pack(x):
        log("pack", x)
        return x * 10

    def run(p):
        log("run", p // 10)
        return p + 1

    def collect(h):
        log("collect", (h - 1) // 10)
        return h

    out = pipeline_map(pack, run, collect, [0, 1, 2, 3])
    assert out == [1, 11, 21, 31]
    order = {("run", i): k for k, (t, i) in enumerate(events) if t == "run"}
    for t, i in events:
        if t == "collect" and i + 1 < 4:
            assert order[("run", i + 1)] < events.index(("collect", i))


def test_pipeline_map_empty_and_single():
    assert pipeline_map(lambda x: x, lambda x: x, lambda x: x, []) == []
    assert pipeline_map(
        lambda x: x + 1, lambda x: x * 2, lambda x: x - 1, [5]
    ) == [11]


def test_pipeline_map_propagates_errors():
    def bad_run(p):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError):
        pipeline_map(lambda x: x, bad_run, lambda x: x, [1, 2])
