"""Equivalence tests: JAX banded kernel vs the numpy oracle engine.

The oracle (rifraf_tpu.ops.align_np) is a faithful re-statement of
/root/reference/src/align.jl; the JAX kernel must agree everywhere in-band.
Also ports the reference's master invariant `check_all_cols`
(/root/reference/test/test_utils.jl:6-23): for every column j,
max_i(A[i,j] + B[i,j]) == A[end,end].
"""

import numpy as np
import pytest

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_np
from rifraf_tpu.ops.align_jax import (
    backward_batch,
    band_height,
    band_to_banded_array,
    forward_batch,
    traceback_batch,
)
from rifraf_tpu.utils.constants import BASES, encode_seq

SCORES = Scores.from_error_model(ErrorModel(1.0, 5.0, 5.0))


def random_case(rng, slen, tlen, bandwidth):
    t = rng.integers(0, 4, size=tlen).astype(np.int8)
    s = rng.integers(0, 4, size=slen).astype(np.int8)
    log_p = rng.uniform(-3.0, -0.5, size=slen)
    return t, make_read_scores(s, log_p, bandwidth, SCORES)


def assert_band_equal(jax_band, oracle: align_np.BandedArray, slen, tlen, bw):
    got = band_to_banded_array(np.asarray(jax_band), slen, tlen, bw)
    want = oracle.dense(default=-np.inf)
    have = got.dense(default=-np.inf)
    np.testing.assert_allclose(have, want, rtol=1e-9, atol=1e-9)


CASES = [
    (10, 10, 3),
    (8, 12, 3),
    (12, 8, 3),
    (30, 25, 5),
    (1, 5, 2),
    (5, 1, 2),
    (40, 40, 9),
]


@pytest.mark.parametrize("slen,tlen,bw", CASES)
def test_forward_matches_oracle(slen, tlen, bw):
    rng = np.random.default_rng(slen * 1000 + tlen * 10 + bw)
    t, rs = random_case(rng, slen, tlen, bw)
    oracle = align_np.forward(t, rs)
    batch = batch_reads([rs], dtype=np.float64)
    bands, moves, scores, geom = forward_batch(t, batch)
    assert_band_equal(bands[0], oracle, slen, tlen, bw)
    d_end = oracle[slen, tlen]
    np.testing.assert_allclose(float(scores[0]), d_end, rtol=1e-9)


@pytest.mark.parametrize("slen,tlen,bw", CASES)
def test_backward_matches_oracle(slen, tlen, bw):
    rng = np.random.default_rng(slen * 991 + tlen * 13 + bw)
    t, rs = random_case(rng, slen, tlen, bw)
    oracle = align_np.backward(t, rs)
    batch = batch_reads([rs], dtype=np.float64)
    bands, scores, geom = backward_batch(t, batch)
    assert_band_equal(bands[0], oracle, slen, tlen, bw)
    np.testing.assert_allclose(float(scores[0]), oracle[0, 0], rtol=1e-9)


def test_check_all_cols_invariant():
    """The reference's master oracle (test_utils.jl:6-23)."""
    rng = np.random.default_rng(42)
    for _ in range(5):
        slen = int(rng.integers(5, 40))
        tlen = int(rng.integers(5, 40))
        t, rs = random_case(rng, slen, tlen, 6)
        batch = batch_reads([rs], dtype=np.float64)
        A, _, scores, _ = forward_batch(t, batch)
        B, _, _ = backward_batch(t, batch)
        A = np.asarray(A[0])
        B = np.asarray(B[0])
        total = float(scores[0])
        both = A + B
        both[~np.isfinite(both)] = -np.inf
        for j in range(tlen + 1):
            col_max = np.max(both[:, j])
            np.testing.assert_allclose(col_max, total, rtol=1e-9, err_msg=f"col {j}")


@pytest.mark.slow
def test_batched_mixed_lengths():
    """Reads of different lengths / bandwidths in one padded batch."""
    rng = np.random.default_rng(7)
    tlen = 20
    t = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for slen, bw in [(15, 3), (20, 5), (26, 4), (9, 6)]:
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -0.5, size=slen)
        reads.append(make_read_scores(s, log_p, bw, SCORES))
    batch = batch_reads(reads, dtype=np.float64)
    bands, moves, scores, geom = forward_batch(t, batch)
    for k, rs in enumerate(reads):
        oracle = align_np.forward(t, rs)
        np.testing.assert_allclose(
            float(scores[k]), oracle[len(rs), tlen], rtol=1e-9
        )
        assert_band_equal(bands[k], oracle, len(rs), tlen, rs.bandwidth)


@pytest.mark.slow
def test_template_bucket_padding():
    """Padded template columns must not affect scores (dynamic tlen)."""
    rng = np.random.default_rng(11)
    t, rs = random_case(rng, 18, 15, 4)
    batch = batch_reads([rs], dtype=np.float64)
    t_padded = np.concatenate([t, np.zeros(10, dtype=np.int8)])
    K = band_height(batch, 15)
    _, _, s1, _ = forward_batch(t, batch, tlen=15, K=K)
    _, _, s2, _ = forward_batch(t_padded, batch, tlen=15, K=K)
    np.testing.assert_allclose(float(s1[0]), float(s2[0]), rtol=1e-12)


def path_score(moves, t, rs):
    """Total log10 score of a traceback path, replayed by hand."""
    i = j = 0
    total = 0.0
    for m in moves:
        if m == align_np.TRACE_MATCH:
            i += 1
            j += 1
            total += (
                rs.match_scores[i - 1]
                if rs.seq[i - 1] == t[j - 1]
                else rs.mismatch_scores[i - 1]
            )
        elif m == align_np.TRACE_INSERT:
            i += 1
            total += rs.ins_scores[i - 1]
        elif m == align_np.TRACE_DELETE:
            j += 1
            total += rs.del_scores[i]
        else:
            raise AssertionError(f"bad move {m}")
    assert i == len(rs) and j == len(t)
    return total


@pytest.mark.slow
def test_traceback_matches_oracle():
    rng = np.random.default_rng(3)
    tlen = 22
    t = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for slen in [18, 22, 25]:
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -0.5, size=slen)
        reads.append(make_read_scores(s, log_p, 5, SCORES))
    batch = batch_reads(reads, dtype=np.float64)
    bands, moves, scores, geom = forward_batch(t, batch, want_moves=True)
    paths = traceback_batch(np.asarray(moves), geom)
    for k, rs in enumerate(reads):
        oracle, amoves = align_np.forward_moves(t, rs)
        want = align_np.backtrace(amoves)
        got = paths[k]
        if got != want:
            # exact score ties may be broken differently; both paths must be
            # optimal (same total score) and complete
            np.testing.assert_allclose(
                path_score(got, t, rs), oracle[len(rs), tlen], rtol=1e-9
            )
        # the path always reconstructs the full pair of sequences
        at, as_ = align_np.moves_to_aligned_seqs(got, t, rs.seq)
        assert (as_[as_ >= 0] == rs.seq).all()
        assert (at[at >= 0] == t).all()


@pytest.mark.slow
def test_traceback_stats_match_host_walk():
    """The device scan-based traceback statistics (error counts + edit
    indicator table) must equal the host pointer-chase walk on the same
    move bands, over many random shapes."""
    import jax
    import jax.numpy as jnp

    from rifraf_tpu.engine.generate import moves_to_proposals
    from rifraf_tpu.engine.proposals import Deletion, Insertion, Substitution
    from rifraf_tpu.ops.align_jax import _traceback_stats_one

    rng = np.random.default_rng(11)
    for trial in range(12):
        tlen = int(rng.integers(8, 40))
        t = rng.integers(0, 4, size=tlen).astype(np.int8)
        reads = []
        for _ in range(4):
            slen = int(rng.integers(max(4, tlen - 6), tlen + 7))
            s = rng.integers(0, 4, size=slen).astype(np.int8)
            log_p = rng.uniform(-3.0, -0.3, size=slen)
            reads.append(make_read_scores(s, log_p, 4, SCORES))
        tp = np.pad(t, (0, int(rng.integers(0, 5))))  # bucket padding
        batch = batch_reads(reads, dtype=np.float64)
        bands, moves, scores, geom = forward_batch(
            tp, batch, tlen=tlen, want_moves=True
        )
        K = np.asarray(moves).shape[1]
        stats = jax.vmap(
            _traceback_stats_one, in_axes=(0, 0, None, 0, None)
        )
        nerr, edits = stats(moves, jnp.asarray(batch.seq), jnp.asarray(tp, jnp.int8), geom, K)
        nerr, edits = np.asarray(nerr), np.asarray(edits)
        paths = traceback_batch(np.asarray(moves), geom)
        T1 = np.asarray(moves).shape[2]
        for k, rs in enumerate(reads):
            # error count vs host walk on the identical path
            i = j = errs = 0
            for m in paths[k]:
                di, dj = align_np.OFFSETS[m]
                i += di
                j += dj
                if m == align_np.TRACE_MATCH:
                    errs += int(rs.seq[i - 1] != t[j - 1])
                else:
                    errs += 1
            assert nerr[k] == errs, (trial, k)
            # edit table vs host moves_to_proposals
            want = np.zeros((T1, 9), bool)
            for p in moves_to_proposals(paths[k], t, rs.seq):
                if isinstance(p, Substitution):
                    want[p.pos, p.base] = True
                elif isinstance(p, Insertion):
                    want[p.pos, 4 + p.base] = True
                else:
                    want[p.pos, 8] = True
            assert (edits[k].astype(bool) == want).all(), (trial, k)


@pytest.mark.slow
def test_trim_and_skew_match_oracle():
    rng = np.random.default_rng(19)
    t, rs = random_case(rng, 20, 14, 5)
    batch = batch_reads([rs], dtype=np.float64)
    for trim, skew in [(True, False), (False, True), (True, True)]:
        oracle = align_np.forward(t, rs, trim=trim, skew_matches=skew)
        bands, _, scores, _ = forward_batch(
            t, batch, trim=trim, skew_matches=skew
        )
        np.testing.assert_allclose(
            float(scores[0]), oracle[len(rs), 14], rtol=1e-9
        )
        assert_band_equal(bands[0], oracle, 20, 14, 5)


def test_perfect_match_score_is_match_sum():
    """Self-alignment: score equals the sum of match scores
    (test_align.jl:269-284 spirit)."""
    seq = encode_seq("ACGTACGTACGT")
    log_p = np.full(len(seq), -2.0)
    rs = make_read_scores(seq, log_p, 4, SCORES)
    batch = batch_reads([rs], dtype=np.float64)
    _, _, scores, _ = forward_batch(seq, batch)
    np.testing.assert_allclose(
        float(scores[0]), float(np.sum(rs.match_scores)), rtol=1e-9
    )
