"""Cluster-level parallel sweep: concurrent jobs == sequential, and the
CLI fan-out path (the reference's pmap over files, scripts/rifraf.jl:190-191).
"""

import numpy as np
import pytest

from rifraf_tpu.cli.consensus import main as consensus_main
from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.io.fastx import read_fasta, write_fastq
from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.parallel.cluster import (
    resolve_jobs_flag,
    sweep_clusters,
)
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.constants import decode_seq

ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _make_cluster(seed, length=60, nseqs=6):
    rng = np.random.default_rng(seed)
    _, template, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=nseqs, length=length, error_rate=0.02, rng=rng,
        seq_errors=ERRORS,
    )
    return template, seqs, phreds


@pytest.mark.slow
def test_sweep_matches_sequential():
    """Concurrent workers produce bit-identical results to a plain loop,
    in job order, regardless of completion order."""
    clusters = [_make_cluster(seed) for seed in range(4)]

    def job(c):
        _, seqs, phreds = c
        return rifraf(seqs, phreds=phreds, params=RifrafParams())

    seq_results = sweep_clusters(job, clusters, max_workers=1)
    par_results = sweep_clusters(job, clusters, max_workers=4)
    assert len(par_results) == len(seq_results) == 4
    for seq_r, par_r in zip(seq_results, par_results):
        assert np.array_equal(seq_r.consensus, par_r.consensus)
        assert seq_r.state.converged == par_r.state.converged


@pytest.mark.slow
def test_sweep_recovers_templates():
    clusters = [_make_cluster(seed, length=50) for seed in (10, 11, 13)]

    def job(c):
        _, seqs, phreds = c
        return rifraf(seqs, phreds=phreds, params=RifrafParams())

    results = sweep_clusters(job, clusters, max_workers=3)
    for (template, _, _), r in zip(clusters, results):
        assert decode_seq(r.consensus) == decode_seq(template)


@pytest.mark.slow
def test_sweep_empty_and_single():
    assert sweep_clusters(lambda x: x + 1, []) == []
    assert sweep_clusters(lambda x: x + 1, [41]) == [42]


def test_resolve_jobs_flag():
    import jax

    n_dev = len(jax.devices())
    assert resolve_jobs_flag(0, 100) == min(100, n_dev)
    assert resolve_jobs_flag(0, 1) == 1
    assert resolve_jobs_flag(3, 100) == 3
    assert resolve_jobs_flag(7, 2) == 2


@pytest.mark.slow
def test_cli_jobs_matches_sequential(tmp_path):
    """The CLI sweep with --jobs N writes the same FASTA as --jobs 1."""
    for k in range(3):
        _, seqs, phreds = _make_cluster(20 + k, length=50)
        write_fastq(
            str(tmp_path / f"reads-{k}.fastq"), seqs,
            [np.asarray(p, dtype=np.int8) for p in phreds],
        )
    glob_in = str(tmp_path / "reads-*.fastq")
    out_seq = str(tmp_path / "seq.fasta")
    out_par = str(tmp_path / "par.fasta")
    assert consensus_main(["--jobs", "1", "1,2,2", glob_in, out_seq]) == 0
    assert consensus_main(["--jobs", "3", "1,2,2", glob_in, out_par]) == 0
    got_seq = [decode_seq(s) for s in read_fasta(out_seq)]
    got_par = [decode_seq(s) for s in read_fasta(out_par)]
    assert got_seq == got_par
    assert len(got_seq) == 3


@pytest.mark.slow
def test_sweep_propagates_job_failure():
    """A failing job fails the whole sweep (the reference re-throws
    RemoteException from workers, scripts/rifraf.jl:204-207)."""
    import pytest

    def job(x):
        if x == 2:
            raise ValueError("boom")
        return x

    with pytest.raises(ValueError, match="boom"):
        sweep_clusters(job, [1, 2, 3], max_workers=3)
