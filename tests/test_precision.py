"""Mixed-precision band store (band_dtype) oracle harness.

``band_dtype="f32"`` must be BIT-identical to the pre-option code: the
f32 path has no casts, so every output — band tables, packed scores,
consensus — compares with assert_array_equal.

``band_dtype="bf16"`` stores the materialized forward/backward band
tables in bfloat16 while every max-plus accumulation, rescoring, and
convergence decision stays float32 (store-narrow / accumulate-wide).
Tolerance gates here are LOG10-scaled (``assert_close(atol_log10=)``):
band values are log10 probabilities, so an absolute tolerance in log
space is a relative tolerance on probability. bf16 keeps ~8 mantissa
bits (relative step 2^-8), so a table value x carries absolute error
up to ~|x|/256 — the gates below allow that plus slack, and the
ACCURACY gate requires the end-to-end consensus to match f32 exactly
on well-conditioned clusters (the precision loss must shave HBM bytes,
not bases).

The CI kernels matrix runs this file once per band dtype by exporting
``RIFRAF_TPU_BAND_DTYPE`` — unset, both parametrizations run.
"""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = pytest.importorskip("jax.numpy")

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax
from rifraf_tpu.ops.fused import fused_step_full

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))

_ENV_DTYPE = os.environ.get("RIFRAF_TPU_BAND_DTYPE", "")
BAND_DTYPES = [_ENV_DTYPE] if _ENV_DTYPE else ["f32", "bf16"]


def assert_close(got, want, atol_log10=-6.0, what="values"):
    """Compare two log10-space arrays: identical ±inf masks, finite
    entries within ``10**atol_log10`` absolute (= relative in
    probability space). The f32 oracle gates at atol_log10=-6 by
    default; bf16 comparisons pass a looser bound derived from the
    table magnitudes."""
    got = np.asarray(got, np.float64)
    want = np.asarray(want, np.float64)
    assert got.shape == want.shape
    fin_g, fin_w = np.isfinite(got), np.isfinite(want)
    mismatched = fin_g != fin_w
    assert not mismatched.any(), (
        f"{what}: {mismatched.sum()} entries differ in finiteness"
    )
    if fin_w.any():
        err = np.abs(got[fin_w] - want[fin_w]).max()
        assert err <= 10.0 ** atol_log10, (
            f"{what}: max |diff| {err:.3e} > 1e{atol_log10:g}"
        )


def _problem(tlen=48, n_reads=5, bw=8, seed=7):
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        seq = template.copy()
        for _ in range(2):
            i = rng.integers(0, len(seq))
            seq[i] = (seq[i] + 1) % 4
        log_p = rng.uniform(-3.0, -1.0, size=len(seq))
        reads.append(make_read_scores(seq, log_p, bw, SCORES))
    return template, batch_reads(reads, dtype=np.float32)


def _run(band_dtype, tlen=48, seed=7, K=48):
    template, batch = _problem(tlen=tlen, seed=seed)
    geom = align_jax.batch_geometry(batch, tlen)
    w = jnp.ones(batch.seq.shape[0], jnp.float32)
    A, B, moves, packed = fused_step_full(
        jnp.asarray(template), batch.seq, batch.match, batch.mismatch,
        batch.ins, batch.dels, geom, w, K, band_dtype=band_dtype,
    )
    return (np.asarray(A), np.asarray(B), np.asarray(moves),
            np.asarray(packed))


def test_f32_band_dtype_is_bit_identical_to_default():
    """band_dtype="f32" inserts NO casts: every output of the fused
    step is bitwise equal to a call that never mentions the option."""
    base = _run("f32")
    template, batch = _problem()
    geom = align_jax.batch_geometry(batch, 48)
    w = jnp.ones(batch.seq.shape[0], jnp.float32)
    ref = fused_step_full(
        jnp.asarray(template), batch.seq, batch.match, batch.mismatch,
        batch.ins, batch.dels, geom, w, 48,
    )
    for got, want in zip(base, ref):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("band_dtype", BAND_DTYPES)
def test_band_tables_within_tolerance_of_f32(band_dtype):
    """The returned (re-widened) band tables stay within the dtype's
    log10-space tolerance of the f32 oracle. For f32 that tolerance is
    exact; for bf16 it is |x|/256 — tables here reach magnitude ~1e2,
    so the gate sits at 10**0 with the measured error far below."""
    ref = _run("f32")
    got = _run(band_dtype)
    atol = -6.0 if band_dtype == "f32" else 0.0
    assert_close(got[0], ref[0], atol_log10=atol, what="A bands")
    assert_close(got[1], ref[1], atol_log10=atol, what="B bands")
    if band_dtype == "bf16":
        # the cast is REAL: values must differ from f32 somewhere
        fin = np.isfinite(ref[0]) & np.isfinite(got[0])
        assert (got[0][fin] != ref[0][fin]).any()


@pytest.mark.parametrize("band_dtype", BAND_DTYPES)
def test_consensus_accuracy_gate(band_dtype):
    """End-to-end accuracy gate: the driver at either band dtype must
    recover the planted template exactly on a well-conditioned cluster
    — bf16 trades table precision for bytes, never for bases."""
    from rifraf_tpu.engine.driver import rifraf
    from rifraf_tpu.engine.params import RifrafParams

    rng = np.random.default_rng(11)
    template = rng.integers(0, 4, 60).astype(np.int8)
    seqs, lps = [], []
    for _ in range(8):
        seq = template.copy()
        i = rng.integers(0, len(seq))
        seq[i] = (seq[i] + 1) % 4
        seqs.append(seq)
        lps.append(np.full(len(seq), -1.5))
    result = rifraf(
        seqs, error_log_ps=lps,
        params=RifrafParams(band_dtype=band_dtype),
    )
    assert result.consensus.tolist() == template.tolist()


@pytest.mark.parametrize("band_dtype", BAND_DTYPES)
def test_driver_band_dtype_consensus_matches_f32(band_dtype):
    """Same cluster, both precisions: identical consensus (scores may
    differ in the bf16 rounding tail)."""
    from rifraf_tpu.engine.driver import rifraf
    from rifraf_tpu.engine.params import RifrafParams

    rng = np.random.default_rng(5)
    template = rng.integers(0, 4, 80).astype(np.int8)
    seqs, lps = [], []
    for _ in range(6):
        seq = template.copy()
        for _ in range(2):
            i = rng.integers(0, len(seq))
            seq[i] = (seq[i] + 1) % 4
        seqs.append(seq)
        lps.append(np.full(len(seq), -1.2))

    def consensus(bd):
        return rifraf(
            seqs, error_log_ps=lps,
            params=RifrafParams(band_dtype=bd),
        ).consensus.tolist()

    assert consensus(band_dtype) == consensus("f32")


def test_input_enc_f32_driver_is_bit_identical():
    """input_enc="f32" (the default) inserts NO casts anywhere: the
    driver's consensus AND score are bit-equal to a run whose params
    never mention the option. (The packed-encoding accuracy harness —
    pack/quantize property bounds plus the kernel grid — lives in
    tests/test_input_encoding.py.)"""
    from rifraf_tpu.engine.driver import rifraf
    from rifraf_tpu.engine.params import RifrafParams

    rng = np.random.default_rng(17)
    template = rng.integers(0, 4, 60).astype(np.int8)
    seqs, lps = [], []
    for _ in range(6):
        seq = template.copy()
        i = rng.integers(0, len(seq))
        seq[i] = (seq[i] + 1) % 4
        seqs.append(seq)
        lps.append(np.full(len(seq), -1.5))
    base = rifraf(seqs, error_log_ps=lps, params=RifrafParams())
    opt = rifraf(seqs, error_log_ps=lps,
                 params=RifrafParams(input_enc="f32"))
    np.testing.assert_array_equal(opt.consensus, base.consensus)
    assert float(opt.state.score) == float(base.state.score)


def test_params_reject_unknown_band_dtype():
    from rifraf_tpu.engine.params import RifrafParams, check_params

    with pytest.raises(ValueError, match="band_dtype"):
        check_params(SCORES, 60, RifrafParams(band_dtype="f16"))
    with pytest.raises(ValueError, match="band_growth"):
        check_params(SCORES, 60, RifrafParams(band_growth="wfa"))
