"""The result-integrity layer: numerical sentinels (guard reduction +
typed decode), deterministic shadow-verification sampling and the
precision-bound divergence test, the silent ``corrupt`` fault kind,
journal CRC verify-on-read, the suspect-device quarantine scoreboard
with its known-answer golden probe, and the supervisor restart ->
probe-before-rejoin interplay.

Fast tests are host-only (plus the per-cluster fallback compiles the
fast serve suites already pay); the fused-step guard reduction, the
guarded sweep equality, and the corrupt-site end-to-end detection run
are marked slow. CI's integrity job runs the fast set under BOTH
``RIFRAF_TPU_FUSED_IMPL`` settings, so each leg exercises one
primary/oracle pairing."""

import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from rifraf_tpu.engine.integrity import (
    GUARD_NAN,
    GUARD_POSINF,
    GUARD_UNDERFLOW,
    NumericalIntegrityError,
    alternate_impl,
    check_finite,
    check_guard,
    decode_guard,
    oracle_impl,
    scores_diverge,
    selected_for_verify,
)
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.io.journal import (
    Journal,
    JournalError,
    _fsync_dir,
    read_journal,
)
from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.parallel.sweep_sharded import (
    SweepResult,
    sweep_clusters_sharded,
)
from rifraf_tpu.serve import (
    ConsensusServer,
    FaultPlan,
    InjectedFaultError,
    ServeConfig,
    ServerStats,
    submit_many,
)
from rifraf_tpu.serve.faults import CORRUPT_BIT, corrupt_value
from rifraf_tpu.serve.quarantine import (
    GOLDEN_LEN,
    GOLDEN_READS,
    DeviceScoreboard,
    device_key,
    golden_problem,
)
from rifraf_tpu.serve.request import Request
from rifraf_tpu.serve.worker import Flush, Worker
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _cluster(nseqs=3, length=30, seed=0):
    rng = np.random.default_rng(seed)
    params = RifrafParams()
    _, _, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=nseqs, length=length, error_rate=0.02, rng=rng,
        seq_errors=SEQ_ERRORS,
    )
    return [
        make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                         params.bandwidth, params.scores)
        for s, p in zip(seqs, phreds)
    ]


def _fast_cfg(**kw):
    """Fallback-path config: no batch-grid compiles."""
    kw.setdefault("batch_max_reads", 1)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("supervise_interval_s", 0.02)
    return ServeConfig(**kw)


def _mk_request(cluster, cfg, rid="t0"):
    from rifraf_tpu.parallel.sweep_sharded import bucket_key, cluster_info

    info = cluster_info(cluster)
    return Request(
        id=rid, cluster=list(cluster), info=info,
        key=bucket_key(info, cfg.read_bucket, cfg.band_bucket,
                       cfg.len_bucket),
        t_submit=time.perf_counter(), deadline=None,
    )


# ------------------------------------------------- guard decode / check


def test_decode_guard():
    assert decode_guard(0) == ()
    assert decode_guard(GUARD_NAN) == ("nan",)
    assert decode_guard(GUARD_POSINF) == ("posinf",)
    assert decode_guard(GUARD_NAN | GUARD_UNDERFLOW) == (
        "nan", "underflow")


def test_check_guard_clean_and_trip():
    check_guard(np.zeros(5), "adapt")  # clean: no raise
    g = np.zeros(5)
    g[2] = GUARD_NAN | GUARD_POSINF
    with pytest.raises(NumericalIntegrityError) as ei:
        check_guard(g, "adapt", device="dev0",
                    lane_map=["r7", "r8", "r9", "r10"])
    err = ei.value
    assert err.code == "numerical_integrity"
    assert err.stage == "adapt"
    assert err.lane == 2
    assert set(err.flags) == {"nan", "posinf"}
    assert err.device == "dev0"
    assert err.context["owner"] == "r9"


def test_check_guard_dense_total_lane():
    g = np.zeros(4)
    g[-1] = GUARD_UNDERFLOW
    with pytest.raises(NumericalIntegrityError) as ei:
        check_guard(g, "stage")
    assert ei.value.lane == -1
    assert "dense total" in str(ei.value)


def test_check_guard_nonfinite_flag_word_is_a_trip():
    """A corrupted guard WORD (NaN where an int bitmask should be) is
    itself a trip, never a silent pass."""
    g = np.zeros(3)
    g[0] = np.nan
    with pytest.raises(NumericalIntegrityError) as ei:
        check_guard(g, "adapt")
    assert "nan" in ei.value.flags


def test_check_finite():
    check_finite([-1.0, -np.inf], "score")  # -inf is the legal sentinel
    with pytest.raises(NumericalIntegrityError):
        check_finite([0.0, np.nan], "score")
    with pytest.raises(NumericalIntegrityError) as ei:
        check_finite(np.inf, "total", what="total")
    assert ei.value.lane == -1


def test_pack_layout_guard_appended_last():
    from rifraf_tpu.ops.fused import pack_layout

    for want_stats in (False, True):
        base = pack_layout(5, 33, want_stats)
        guarded = pack_layout(5, 33, want_stats, want_guard=True)
        # every pre-guard offset is untouched: integrity off stays
        # byte-identical, integrity on only APPENDS
        for name, sl in base.items():
            assert guarded[name] == sl
        assert set(guarded) - set(base) == {"guard"}
        a, b = guarded["guard"]
        assert b - a == 5 + 1  # per-read words + the dense-total word
        assert a == max(stop for _, stop in base.values())


# -------------------------------------------- shadow-verify primitives


def test_selected_for_verify_deterministic_and_monotone():
    digests = [f"cluster-{i}" for i in range(400)]
    sel_20 = {d for d in digests if selected_for_verify(d, 0.2)}
    sel_60 = {d for d in digests if selected_for_verify(d, 0.6)}
    # deterministic (digest-keyed, no RNG state) and monotone in the
    # fraction: raising verify_fraction only ADDS results
    assert sel_20 == {d for d in digests if selected_for_verify(d, 0.2)}
    assert sel_20 <= sel_60
    assert 0 < len(sel_20) < len(sel_60) < len(digests)
    assert not any(selected_for_verify(d, 0.0) for d in digests)
    assert all(selected_for_verify(d, 1.0) for d in digests)


def test_scores_diverge_precision_bounds():
    # f32: the precision harness's 1e-6 absolute log10 bound
    assert not scores_diverge(-100.0, -100.0 + 5e-7)[0]
    assert scores_diverge(-100.0, -100.0 + 1e-5)[0]
    # bf16: tolerance scales with |score| like the bf16 band store's
    # per-value error
    diverged, tol = scores_diverge(-1000.0, -1010.0, "bf16")
    assert not diverged and tol > 10
    assert scores_diverge(-1000.0, -1030.0, "bf16")[0]
    # finiteness mismatches always diverge; matching -inf does not
    assert scores_diverge(-np.inf, -100.0)[0]
    assert not scores_diverge(-np.inf, -np.inf)[0]
    assert scores_diverge(np.inf, -np.inf)[0]


def test_oracle_impl_pins_alternate_routing():
    from rifraf_tpu.ops.fused_pallas import fused_impl

    primary = fused_impl()
    alt = alternate_impl()
    assert {primary, alt} == {"mega", "split"}
    with oracle_impl() as impl:
        assert impl == alt
        assert os.environ["RIFRAF_TPU_FUSED_IMPL"] == alt
        assert fused_impl() == alt
    assert fused_impl() == primary  # env restored on exit


# --------------------------------------------- the corrupt fault kind


def test_corrupt_value_involution():
    for x in (-12.375, 0.0, 3.14159, -1e-30):
        y = corrupt_value(x, 51)
        assert y != x
        assert corrupt_value(y, 51) == x  # flip twice = identity
    # the default bit is the float64 top mantissa bit
    assert corrupt_value(1.0) == 1.5


def test_fault_plan_corrupt_parse_and_fire_skips():
    plan = FaultPlan.parse("fetch:corrupt:n=2,bit=12")
    s = plan.specs[0]
    assert (s.site, s.kind, s.n, s.bit) == ("fetch", "corrupt", 2, 12)
    plan.fire("fetch")  # the raising path ignores corrupt specs
    assert s.fired == 0
    assert plan.corrupt("fetch") == 12
    assert plan.corrupt("fetch") == 12
    assert plan.corrupt("fetch") is None  # n=2 exhausted
    assert plan.corrupt("dispatch") is None  # other sites unaffected
    snap = plan.snapshot()
    assert snap["site_calls"]["fetch~corrupt"] == 3
    assert snap["specs"][0]["fired"] == 2


def test_fault_plan_corrupt_counter_independent_of_fire():
    plan = FaultPlan.parse("fetch:corrupt:n=1,after=2;fetch:error:n=1")
    with pytest.raises(InjectedFaultError):
        plan.fire("fetch")
    # raising invocations must NOT advance the corrupt gating counter
    assert plan.corrupt("fetch") is None  # corrupt invocation 0
    assert plan.corrupt("fetch") is None  # 1
    assert plan.corrupt("fetch") == CORRUPT_BIT  # 2: after=2 satisfied


def test_worker_maybe_corrupt_counts_and_flips():
    cfg = _fast_cfg(supervise=False, faults="fetch:corrupt:n=1,bit=50")
    stats = ServerStats()
    w = Worker(cfg, stats)
    res = SweepResult(consensus=np.array([1, 2], np.int8), score=-42.5,
                      n_iters=3, converged=True)
    out = w._maybe_corrupt(res)
    assert out.score == corrupt_value(-42.5, 50)
    assert out.score != -42.5
    assert np.array_equal(out.consensus, res.consensus)
    assert stats.integrity()["injected_corrupt"] == 1
    assert w._maybe_corrupt(res) is res  # plan exhausted: untouched


# ----------------------------------------------- journal CRC satellite


def test_journal_crc_round_trip(tmp_path):
    p = str(tmp_path / "run.journal.jsonl")
    with Journal(p, header={"fingerprint": "abc"}) as j:
        j.append({"kind": "chunk", "i": 0})
        j.append({})  # the empty-record splice edge case
    records, torn = read_journal(p)
    assert not torn
    assert records == [
        {"kind": "header", "fingerprint": "abc"},
        {"kind": "chunk", "i": 0},
        {},
    ]
    raw = open(p).read()
    assert raw.count('"crc"') == 3  # every appended line carries one


def test_journal_in_place_corruption_refuses_resume(tmp_path):
    p = str(tmp_path / "run.journal.jsonl")
    with Journal(p, header={"fingerprint": "abc"}) as j:
        j.append({"kind": "chunk", "i": 0})
        j.append({"kind": "chunk", "i": 1})
    lines = open(p).readlines()
    # flip a value INSIDE record 1's body: still complete JSON, so only
    # the CRC can catch it
    lines[1] = lines[1].replace('"i": 0', '"i": 7')
    with open(p, "w") as fh:
        fh.writelines(lines)
    with pytest.raises(JournalError, match="record 1"):
        read_journal(p)


def test_journal_torn_tail_still_tolerated(tmp_path):
    p = str(tmp_path / "run.journal.jsonl")
    with Journal(p, header={"fingerprint": "abc"}) as j:
        j.append({"kind": "chunk", "i": 0})
    with open(p, "ab") as fh:
        fh.write(b'{"kind": "chu')  # the append a crash interrupted
    records, torn = read_journal(p)
    assert torn
    assert [r.get("i") for r in records] == [None, 0]


def test_journal_legacy_without_crc_still_reads(tmp_path):
    p = str(tmp_path / "legacy.journal.jsonl")
    with open(p, "w") as fh:
        fh.write('{"kind": "header", "fingerprint": "abc"}\n')
        fh.write('{"kind": "chunk", "i": 0}\n')
    records, torn = read_journal(p)
    assert not torn
    assert records[1] == {"kind": "chunk", "i": 0}


def test_fsync_dir_best_effort():
    _fsync_dir("/nonexistent/dir/for/sure/x.jsonl")  # silently skipped
    _fsync_dir(os.path.join(os.getcwd(), "x.jsonl"))


# --------------------------------------- quarantine scoreboard + probe


def test_scoreboard_threshold_and_reinstate():
    sb = DeviceScoreboard(threshold=2)
    assert not sb.record_trip("d0", "guard")
    # crossing the threshold quarantines and returns True exactly once
    assert sb.record_trip("d0", "divergence")
    assert sb.is_quarantined("d0")
    assert not sb.record_trip("d0", "guard")
    assert sb.any_quarantined()
    assert not sb.is_quarantined("d1")
    # a failing probe keeps it out; a passing one reinstates and zeroes
    # the trip counters (the device starts clean)
    assert sb.note_probe("d0", ok=False)
    assert not sb.note_probe("d0", ok=True)
    assert not sb.is_quarantined("d0")
    assert sb.snapshot()["d0"] == {
        "quarantined": False, "guard_trips": 0, "divergences": 0,
        "probes_pass": 1, "probes_fail": 1,
    }


def test_scoreboard_threshold_zero_counts_without_evicting():
    sb = DeviceScoreboard(threshold=0)
    for _ in range(5):
        assert not sb.record_trip(None, "guard")
    assert not sb.is_quarantined(None)
    assert sb.snapshot()["default"]["guard_trips"] == 5
    with pytest.raises(ValueError):
        sb.record_trip(None, "bogus")


def test_device_key():
    assert device_key(None) == "default"
    assert device_key("TPU:3") == "TPU:3"


def test_golden_problem_deterministic():
    cfg = ServeConfig()
    c1, t1 = golden_problem(cfg)
    c2, t2 = golden_problem(cfg)
    assert len(t1) == GOLDEN_LEN
    assert len(c1) == GOLDEN_READS
    assert np.array_equal(t1, t2)
    for r1, r2 in zip(c1, c2):
        assert np.array_equal(r1.seq, r2.seq)
        assert np.array_equal(r1.seq, t1)  # error-free copies


def test_worker_note_trip_quarantines_at_threshold():
    cfg = _fast_cfg(supervise=False, guard=True)
    stats = ServerStats()
    sb = DeviceScoreboard(threshold=2)
    w = Worker(cfg, stats, scoreboard=sb)
    w._note_trip("guard")
    assert "device_quarantined" not in stats.integrity()
    w._note_trip("divergence")
    ctr = stats.integrity()
    assert ctr["guard_trips"] == 1
    assert ctr["divergence_trips"] == 1
    assert ctr["device_quarantined"] == 1
    assert sb.is_quarantined(None)


def test_retry_ladder_scores_integrity_cause():
    """A tripped sentinel entering the ladder also scores against the
    worker's device on the shared scoreboard."""
    cfg = _fast_cfg(supervise=False, guard=True, max_retries=0)
    stats = ServerStats()
    sb = DeviceScoreboard(threshold=1)
    w = Worker(cfg, stats, scoreboard=sb)
    req = _mk_request(_cluster(), cfg)
    err = NumericalIntegrityError("adapt", 0, GUARD_NAN)
    w._retry_or_fail(Flush("batch", [req]), err)
    assert sb.is_quarantined(None)
    assert stats.integrity()["guard_trips"] == 1
    res = req.future.result(timeout=0)
    assert not res.ok  # budget 0: typed failure, not a hang


# ------------------------------------------ shadow verification (serve)


def test_worker_shadow_verify_catches_corrupted_score():
    cfg = _fast_cfg(supervise=False, verify_fraction=1.0)
    stats = ServerStats()
    w = Worker(cfg, stats, scoreboard=DeviceScoreboard(threshold=9))
    req = _mk_request(_cluster(), cfg)
    good = w._run_fallback(req)  # ground truth via the worker's rung 2
    # a clean result verifies clean (no replacement)
    assert w._maybe_verify(req, good) is None
    ctr = stats.integrity()
    assert ctr["verify_sampled"] == 1 and ctr["verify_ok"] == 1
    # a silently corrupted score is detected and REPLACED by the oracle
    bad = good._replace(score=corrupt_value(good.score))
    repl = w._maybe_verify(req, bad)
    assert repl is not None
    assert repl.score == pytest.approx(good.score, abs=1e-6)
    assert np.array_equal(repl.consensus, good.consensus)
    ctr = stats.integrity()
    assert ctr["verify_divergence"] == 1
    assert ctr["verify_recovered"] == 1
    assert ctr["divergence_trips"] == 1


# --------------------- supervisor restart -> golden-probe interplay


def test_restart_probe_gates_rejoin_and_parks_on_failure(monkeypatch):
    """A restarted worker must PASS the known-answer probe before
    rejoining the round-robin; while it keeps failing, the slot stays
    parked (re-probed, NOT restart-looped) and its requeued work waits
    for a clean pass."""
    probe_ok = {"ok": False}

    def fake_probe(self):
        self._last_probe = time.perf_counter()
        ok = probe_ok["ok"]
        self.stats.count("probe_pass" if ok else "probe_fail")
        if self.scoreboard is not None:
            was = self.scoreboard.is_quarantined(self.device)
            self.scoreboard.note_probe(self.device, ok)
            if ok and was:
                self.stats.count("device_reinstated")
        return ok

    monkeypatch.setattr(Worker, "golden_probe", fake_probe)
    cfg = _fast_cfg(guard=True, probe_interval_s=0.01,
                    faults="fallback:crash:n=1")
    srv = ConsensusServer(cfg)
    try:
        fut = srv.submit(_cluster())
        deadline = time.perf_counter() + 60
        while time.perf_counter() < deadline:
            ctr = srv.health().get("integrity", {}).get("counters", {})
            if ctr.get("probe_fail", 0) >= 3:
                break
            time.sleep(0.02)
        h = srv.health()
        # exactly ONE restart: the parked slot re-probes without
        # burning more restart budget, however long the probe fails
        assert h["worker_restarts"] == 1
        assert h["integrity"]["parked_workers"] == [0]
        assert h["integrity"]["devices"]["default"]["quarantined"]
        assert h["integrity"]["counters"]["probe_fail"] >= 3
        assert not fut.done()  # the requeued work waits, not fails
        probe_ok["ok"] = True
        res = fut.result(timeout=60)
        assert res.ok
        h = srv.health()
        assert h["integrity"]["parked_workers"] == []
        assert h["worker_restarts"] == 1
        assert h["integrity"]["counters"]["probe_pass"] >= 1
        assert h["integrity"]["counters"]["device_reinstated"] >= 1
        assert not h["integrity"]["devices"]["default"]["quarantined"]
    finally:
        srv.close()


# ------------------------------------------------------- slow: on-device


@pytest.mark.slow
def test_fused_guard_layout_identical_and_flags_nan():
    """want_guard appends flags without perturbing a single pre-guard
    word; a NaN poisoned into one read's inputs trips exactly that
    read's guard lane."""
    import jax.numpy as jnp

    from rifraf_tpu.models.errormodel import Scores
    from rifraf_tpu.models.sequences import batch_reads
    from rifraf_tpu.ops import align_jax
    from rifraf_tpu.ops.fused import fused_step_full, pack_layout

    scores = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0))
    rng = np.random.default_rng(3)
    tlen, n_reads = 48, 7
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(tlen - 5, tlen + 6))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -0.5, size=slen)
        reads.append(make_read_scores(s, log_p, 8, scores))
    batch = batch_reads(reads, dtype=np.float64)
    K = ((align_jax.band_height(batch, tlen) + 7) // 8) * 8
    geom = align_jax.batch_geometry(batch, tlen)
    t = jnp.asarray(np.pad(template, (0, 8)), jnp.int8)
    w = jnp.asarray(rng.uniform(0.5, 2.0, n_reads))
    args = (t, jnp.asarray(batch.seq), jnp.asarray(batch.match),
            jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
            jnp.asarray(batch.dels), geom, w)
    T1 = t.shape[0] + 1

    _, _, _, packed = fused_step_full(*args, K, False, True)
    _, _, _, packed_g = fused_step_full(*args, K, False, True,
                                        want_guard=True)
    lay = pack_layout(n_reads, T1, True)
    lay_g = pack_layout(n_reads, T1, True, want_guard=True)
    ref, chk = np.asarray(packed), np.asarray(packed_g)
    for name, (a, b) in lay.items():
        np.testing.assert_array_equal(
            chk[a:b], ref[a:b],
            err_msg=f"guarded layout perturbed section {name!r}")
    ga, gb = lay_g["guard"]
    assert gb == chk.size
    assert np.all(chk[ga:gb] == 0)  # clean inputs: no flags
    check_guard(chk[ga:gb], "stage")  # no raise

    bad_match = np.array(batch.match)
    bad_match[3] = np.nan  # poison read 3's match scores
    _, _, _, packed_bad = fused_step_full(
        args[0], args[1], jnp.asarray(bad_match), args[3], args[4],
        args[5], geom, w, K, False, True, want_guard=True,
    )
    guard_bad = np.asarray(packed_bad)[ga:gb]
    with pytest.raises(NumericalIntegrityError) as ei:
        check_guard(guard_bad, "stage")
    assert ei.value.lane == 3
    assert "nan" in ei.value.flags


@pytest.mark.slow
def test_sweep_guard_and_verify_bit_identical_when_clean():
    """Integrity ON over healthy inputs changes nothing: the guarded +
    fully-verified sweep returns the plain sweep's results exactly."""
    rng = np.random.default_rng(11)
    params = RifrafParams()
    clusters = []
    for _ in range(3):
        _, _, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=4, length=50, error_rate=0.03, rng=rng,
            seq_errors=SEQ_ERRORS,
        )
        clusters.append([
            make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                             params.bandwidth, params.scores)
            for s, p in zip(seqs, phreds)
        ])
    plain = sweep_clusters_sharded(clusters)
    checked = sweep_clusters_sharded(clusters, guard=True,
                                     verify_fraction=1.0)
    for a, b in zip(plain, checked):
        assert np.array_equal(a.consensus, b.consensus)
        assert a.score == b.score
        assert a.n_iters == b.n_iters
        assert a.converged == b.converged


@pytest.mark.slow
def test_serve_corrupt_faults_detected_and_recovered():
    """End-to-end under fire: fetch-site corrupt faults at
    verify_fraction=1.0 — every injected corruption detected, every
    answer bit-identical to the unfaulted reference, the poisoned
    device quarantined (and reinstated by the golden probe, since the
    chip itself is healthy)."""
    clusters = [_cluster(seed=s) for s in range(8)]
    base = dict(max_wait_ms=5.0, supervise=False,
                result_timeout_s=300.0)
    with ConsensusServer(ServeConfig(**base)) as ref_srv:
        ref = submit_many(clusters, server=ref_srv)
    assert all(r.ok for r in ref)

    srv = ConsensusServer(ServeConfig(
        guard=True, verify_fraction=1.0, quarantine_threshold=2,
        probe_interval_s=0.01, faults="fetch:corrupt:n=3", **base))
    # wave 1 rides the corrupt plan; the second divergence crosses the
    # threshold and quarantines the (only) device
    out = submit_many(clusters[:6], server=srv)
    # wave 2 arrives at a quarantined worker: its run_loop requeues the
    # flush and runs the REAL golden probe — the chip is healthy (the
    # corruption was injected, n=3 exhausted), so it reinstates and
    # serves the requeued work
    out += submit_many(clusters[6:], server=srv)
    health = srv.health()
    srv.close()

    assert all(r.ok for r in out)  # availability under fire: 100%
    for r, g in zip(out, ref):
        assert np.array_equal(r.consensus, g.consensus)
        assert r.score == g.score  # recovered answers bit-identical
    ctr = health["integrity"]["counters"]
    assert ctr["injected_corrupt"] == 3
    assert ctr["verify_divergence"] == 3  # 100% detection
    assert ctr["verify_recovered"] == 3
    assert ctr["device_quarantined"] >= 1
    assert ctr["quarantine_requeued"] >= 1
    assert ctr["probe_pass"] >= 1  # healthy chip reinstated
    assert ctr["device_reinstated"] >= 1
    assert not health["integrity"]["devices"]["default"]["quarantined"]
    assert sum(1 for r in out if r.path == "verified") == 3
