"""Bandwidth growth policies (engine.bandgrowth): the blunt doubling
port and the WFA-style adaptive policy, plus their integration with the
sweep planner's heterogeneous-K re-bucketing."""

import numpy as np
import pytest

from rifraf_tpu.engine.bandgrowth import (
    ADAPTIVE_ENTRY_BW,
    MAX_BANDWIDTH_DOUBLINGS,
    adaptive_entry,
    check_band_growth,
    grow_bandwidths,
)

BIG = np.iinfo(np.int64).max


def _args(n, bw=8, entry=8, thr=0, old=BIG, tlen=10_000, slen=10_000):
    """Broadcast helper: everything flagged for growth by default."""
    return dict(
        bandwidths=np.full(n, bw, np.int64),
        fixed=np.zeros(n, bool),
        old_errors=np.full(n, old, np.int64),
        n_errors=np.full(n, 5, np.int64),
        thresholds=np.full(n, thr, np.int64),
        entry_bw=np.full(n, entry, np.int64),
        tlen=tlen,
        slen=slen,
    )


def test_check_band_growth():
    assert check_band_growth("double") == "double"
    assert check_band_growth("adaptive") == "adaptive"
    with pytest.raises(ValueError, match="band_growth"):
        check_band_growth("triple")


def test_adaptive_entry_caps_only_large_bandwidths():
    bw = np.array([4, 16, 17, 100])
    assert adaptive_entry(bw).tolist() == [4, 16, 16, 16]
    assert adaptive_entry(bw).dtype == bw.dtype


def test_double_grows_flagged_reads_x2():
    a = _args(3, bw=8)
    new_bw, new_fixed, new_old = grow_bandwidths(**a)
    assert new_bw.tolist() == [16, 16, 16]
    assert not new_fixed.any()
    assert new_old.tolist() == [5, 5, 5]
    # inputs untouched (fresh arrays)
    assert a["bandwidths"].tolist() == [8, 8, 8]


def test_double_caps_at_entry_shifted_by_max_doublings():
    """The growth ceiling is entry_bw << MAX_BANDWIDTH_DOUBLINGS —
    keyed on the ORIGINAL entry bandwidth, never the current one."""
    cap = 8 << MAX_BANDWIDTH_DOUBLINGS
    a = _args(1, bw=cap // 2, entry=8)
    new_bw, new_fixed, _ = grow_bandwidths(**a)
    assert new_bw[0] == cap
    # at the cap the read cannot be flagged again: it fixes
    a = _args(1, bw=cap, entry=8)
    new_bw, new_fixed, _ = grow_bandwidths(**a)
    assert new_bw[0] == cap
    assert new_fixed[0]


def test_cap_also_bounded_by_template_and_read_length():
    a = _args(1, bw=8, tlen=12, slen=10_000)
    new_bw, _, _ = grow_bandwidths(**a)
    assert new_bw[0] == 12
    a = _args(1, bw=8, tlen=10_000, slen=9)
    new_bw, _, _ = grow_bandwidths(**a)
    assert new_bw[0] == 9


def test_no_growth_on_converged_reads():
    """A read under threshold, or no longer improving, or already
    fixed, keeps its bandwidth and fixes."""
    a = _args(3, bw=8, thr=10)  # n_errors=5 <= 10: under threshold
    new_bw, new_fixed, _ = grow_bandwidths(**a)
    assert new_bw.tolist() == [8, 8, 8]
    assert new_fixed.all()

    a = _args(1, bw=8)
    a["old_errors"] = np.array([5])  # not improving (5 !< 5)
    new_bw, new_fixed, _ = grow_bandwidths(**a)
    assert new_bw[0] == 8 and new_fixed[0]

    a = _args(1, bw=8)
    a["fixed"] = np.array([True])
    new_bw, new_fixed, _ = grow_bandwidths(**a)
    assert new_bw[0] == 8 and new_fixed[0]


def test_adaptive_requires_edge_hits():
    with pytest.raises(ValueError, match="edge_hits"):
        grow_bandwidths(**_args(1), band_growth="adaptive")


def test_adaptive_touches_only_frontier_flagged_reads():
    """Three flagged reads: one rides the band wall hard, one grazes
    it, one never touches it. Only the wall-riders grow; the
    error-bound read fixes immediately (more band cannot change its
    alignment)."""
    a = _args(3, bw=32)
    new_bw, new_fixed, new_old = grow_bandwidths(
        **a, band_growth="adaptive", edge_hits=np.array([100, 3, 0]))
    # deficit = bucket8(max((eh+1)//2, 1)), never beyond x2 (= +bw)
    assert new_bw.tolist() == [32 + 32, 32 + 8, 32]
    assert new_fixed.tolist() == [False, False, True]
    # old_errors only advances for the reads that grew
    assert new_old.tolist() == [5, 5, BIG]


def test_adaptive_growth_rounds_to_8_grid():
    a = _args(4, bw=64)
    eh = np.array([1, 15, 16, 17])
    new_bw, _, _ = grow_bandwidths(
        **a, band_growth="adaptive", edge_hits=eh)
    # (eh+1)//2 -> 1, 8, 8, 9 -> bucket8 -> 8, 8, 8, 16
    assert (new_bw - 64).tolist() == [8, 8, 8, 16]


def test_adaptive_never_exceeds_doubling():
    a = _args(1, bw=8)
    new_bw, _, _ = grow_bandwidths(
        **a, band_growth="adaptive", edge_hits=np.array([10_000]))
    assert new_bw[0] == 16  # min(bw, deficit) = bw -> x2


def test_adaptive_respects_same_cap_as_double():
    cap = 8 << MAX_BANDWIDTH_DOUBLINGS
    a = _args(1, bw=cap, entry=8)
    new_bw, new_fixed, _ = grow_bandwidths(
        **a, band_growth="adaptive", edge_hits=np.array([50]))
    assert new_bw[0] == cap and new_fixed[0]


def test_policies_ride_2d_cluster_matrices():
    """The sweep executor calls the same function on [G, N] arrays with
    a broadcast [G, 1] template-length column."""
    G, N = 2, 3
    bw = np.full((G, N), 8, np.int64)
    out = grow_bandwidths(
        bw, np.zeros((G, N), bool), np.full((G, N), BIG, np.int64),
        np.full((G, N), 5, np.int64), np.zeros((G, N), np.int64),
        bw, np.array([[100], [12]]), np.full((G, N), 10_000, np.int64),
        band_growth="adaptive", edge_hits=np.full((G, N), 9, np.int64),
    )
    assert out[0].shape == (G, N)
    assert out[0].tolist() == [[16, 16, 16], [12, 12, 12]]


def test_fixed_all_matches_legacy_not_grow_any():
    """The loops break on new_fixed.all(); that must coincide with the
    legacy `not grow.any()` — every non-growing read fixes."""
    a = _args(4, bw=8, thr=10)
    a["n_errors"] = np.array([5, 50, 5, 50])  # two flagged, two under
    new_bw, new_fixed, _ = grow_bandwidths(**a)
    assert new_fixed.tolist() == [True, False, True, False]
    assert (new_bw != a["bandwidths"]).any() == (~new_fixed).any()


# ---- planner integration: deterministic re-bucketing ----


def test_plan_sweep_rebuckets_on_adaptive_entry():
    """Adaptive entry lowers per-read bands to min(bw, 16), so a
    cluster whose caller default was huge lands in a SMALL band bucket
    — deterministically (same inputs, same plan)."""
    pytest.importorskip("jax")
    from rifraf_tpu.models.errormodel import Scores
    from rifraf_tpu.models.sequences import make_read_scores
    from rifraf_tpu.parallel.sweep_sharded import (
        _cluster_infos,
        plan_sweep,
    )

    rng = np.random.default_rng(0)
    sc = Scores(mismatch=-1.0, insertion=-2.0, deletion=-2.0)

    def cluster(bw):
        return [
            make_read_scores(
                rng.integers(0, 4, 80).astype(np.int8),
                np.full(80, -1.2), bw, sc)
            for _ in range(4)
        ]

    clusters = [cluster(64), cluster(64)]
    info_d = _cluster_infos(clusters, "double")
    info_a = _cluster_infos(clusters, "adaptive")
    assert all(i.entry_k > j.entry_k for i, j in zip(info_d, info_a))
    # entry_k from the lowered bands: 2*16 + |len-tlen0| + 1
    assert info_a[0].entry_k == 2 * ADAPTIVE_ENTRY_BW + 1

    plans_a1 = plan_sweep(clusters, band_growth="adaptive")
    plans_a2 = plan_sweep(clusters, band_growth="adaptive")
    assert plans_a1 == plans_a2  # deterministic
    plans_d = plan_sweep(clusters, band_growth="double")
    k_a = min(p.key[3] for p in plans_a1)
    k_d = min(p.key[3] for p in plans_d)
    assert k_a < k_d


# ---- engine integration: both policies reach the same consensus ----


@pytest.mark.slow
def test_sweep_adaptive_matches_double_consensus():
    """sweep_clusters_sharded under band_growth="adaptive" must return
    the same consensus sequences as "double", with settled bandwidth
    mass at-or-below doubling's (the whole point of the policy)."""
    pytest.importorskip("jax")
    from rifraf_tpu.models.errormodel import Scores
    from rifraf_tpu.models.sequences import make_read_scores
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded

    rng = np.random.default_rng(1)
    sc = Scores(mismatch=-1.0, insertion=-2.0, deletion=-2.0)

    def cluster(tlen, n, bw=32):
        tmpl = rng.integers(0, 4, tlen).astype(np.int8)
        reads = []
        for _ in range(n):
            seq = tmpl.copy()
            for _ in range(max(1, tlen // 40)):
                i = rng.integers(0, len(seq))
                seq[i] = (seq[i] + 1) % 4
            reads.append(make_read_scores(
                seq, np.full(len(seq), -1.2), bw, sc))
        return reads

    clusters = [cluster(96, 5), cluster(64, 3), cluster(128, 6)]
    out = {}
    hist = {}
    for bg in ("double", "adaptive"):
        res, st = sweep_clusters_sharded(
            clusters, return_stats=True, band_growth=bg)
        out[bg] = [r.consensus.tolist() for r in res]
        assert st.band_growth == bg
        hist[bg] = dict(st.bw_hist)
    assert out["adaptive"] == out["double"]

    def mean_bw(h):
        return sum(b * c for b, c in h.items()) / sum(h.values())

    assert mean_bw(hist["adaptive"]) <= mean_bw(hist["double"])
