"""Packed streamed-input encoding (params.input_enc) harness.

``input_enc="f32"`` (the default) must leave every path bit-identical:
the f32 kernels read the score planes and read codes exactly as built,
with no casts. ``input_enc="packed"`` packs the read bases 2-bit
(16 codes per int32 lane word) and quantizes the four per-base score
planes to int8 against per-read scale/offset pairs (ops.encoding),
decoding to f32 in-register at VMEM load — accumulate-wide, like the
bf16 band store. The lossy half is PROPERTY-BOUNDED here: the 2-bit
pack round-trips exactly over every code and block height, and the
int8 round trip stays within quantize_error_bound (= scale / 2) on
every masked value. The kernel grid then gates the end product: packed
and f32 fused steps agree on traceback statistics and stay within the
quantization tolerance on the candidate tables, under BOTH fused-step
routings.

Every comparison test runs both encodings in-process (packed is always
judged against the f32 oracle), so there is no per-encoding env gate;
the CI kernels matrix's packed legs run this file — slow kernel grid
included — under each ``RIFRAF_TPU_FUSED_IMPL`` routing.
"""

import types

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = pytest.importorskip("jax.numpy")

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.ops import encoding

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))


# ---- pure encoding properties (no Pallas, fast) ----------------------------


@pytest.mark.parametrize("CB", [1, 5, 15, 16, 17, 21, 32, 33, 48])
def test_pack_roundtrip_exact_over_block_heights(CB):
    """pack_codes_blocked / unpack_codes round-trip every 2-bit code at
    every row-count residue mod 16, including the -9 pad sentinel
    (which packs as an arbitrary code and must come back as its ``& 3``
    image — consumption sites mask pads before use)."""
    rng = np.random.default_rng(CB)
    blk = rng.integers(-9, 4, (3, CB, 128)).astype(np.int32)
    # force full code coverage in row 0
    blk[0, 0, :4] = [0, 1, 2, 3]
    rt = np.asarray(encoding._roundtrip_codes(jnp.asarray(blk)))
    np.testing.assert_array_equal(rt, blk & 3)


def test_packed_rows_word_geometry():
    assert encoding.ceil16(1) == 16
    assert encoding.ceil16(16) == 16
    assert encoding.ceil16(17) == 32
    for CB in (1, 16, 17, 160, 161):
        assert encoding.packed_rows(CB) == -(-CB // 16)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_quantize_roundtrip_within_bound(seed):
    """Every masked value reconstructs within quantize_error_bound
    (scale / 2), across wide, narrow, and constant per-read ranges."""
    rng = np.random.default_rng(seed)
    N, L = 8, 40
    vals = -rng.uniform(0.0, 12.0, (N, L)).astype(np.float32)
    vals[1] = -2.5  # constant row: scale floors at QEPS / QLEVELS
    vals[2] *= 1e-3  # narrow range
    lengths = rng.integers(1, L + 1, N)
    mask = np.arange(L)[None, :] < lengths[:, None]
    q, scale, offset = encoding.quantize_rows(
        jnp.asarray(vals), jnp.asarray(mask)
    )
    deq = np.asarray(encoding.dequantize_rows(q, scale, offset))
    bound = np.asarray(encoding.quantize_error_bound(scale))
    err = np.abs(deq - vals)
    assert (err[mask] <= bound[:, None].repeat(L, 1)[mask] + 1e-7).all()


def test_quantize_empty_mask_rows_are_harmless():
    vals = jnp.zeros((2, 4), jnp.float32)
    mask = jnp.zeros((2, 4), bool)
    q, scale, offset = encoding.quantize_rows(vals, mask)
    assert np.isfinite(np.asarray(scale)).all()
    assert np.isfinite(np.asarray(offset)).all()


def test_check_input_enc():
    assert encoding.check_input_enc("f32") == "f32"
    assert encoding.check_input_enc("packed") == "packed"
    with pytest.raises(ValueError, match="input_enc"):
        encoding.check_input_enc("int8")


def test_params_reject_unknown_input_enc():
    from rifraf_tpu.engine.params import RifrafParams, check_params

    with pytest.raises(ValueError, match="input_enc"):
        check_params(SCORES, 60, RifrafParams(input_enc="int4"))


# ---- kernel grid: packed vs f32 over both fused routings -------------------


def _kernel_problem(tlen=20, n=5, seed=0):
    from rifraf_tpu.ops import fill_pallas
    from rifraf_tpu.ops.align_jax import BandGeometry

    rng = np.random.default_rng(seed)
    Npad, L = 128, 24
    template = rng.integers(0, 4, tlen + 4).astype(np.int8)
    lengths = rng.integers(tlen - 3, tlen + 3, n).astype(np.int32)
    seqs = rng.integers(0, 4, (n, L)).astype(np.int8)
    match = -0.05 - 0.2 * rng.random((n, L)).astype(np.float32)
    mismatch = -1.0 - 1.5 * rng.random((n, L)).astype(np.float32)
    ins = -1.2 - rng.random((n, L)).astype(np.float32)
    dels = -1.1 - rng.random((n, L + 1)).astype(np.float32)
    geom = BandGeometry.make(jnp.asarray(lengths), tlen, 3)
    w = jnp.ones(Npad, jnp.float32)
    ln = jnp.asarray(np.pad(lengths, (0, Npad - n)))

    def bufs(enc):
        return fill_pallas.build_fill_buffers(
            jnp.asarray(seqs), jnp.asarray(match), jnp.asarray(mismatch),
            jnp.asarray(ins), jnp.asarray(dels), ln, Npad, input_enc=enc,
        )

    return template, tlen, geom, w, bufs


@pytest.mark.slow
@pytest.mark.parametrize("impl", ["split", "mega"])
def test_fused_tables_packed_close_to_f32(impl, monkeypatch):
    """Same problem through fused_tables_auto at both encodings, both
    routings (interpret mode): candidate tables within the quantization
    tolerance, traceback statistics identical."""
    monkeypatch.setenv("RIFRAF_TPU_PALLAS_INTERPRET", "1")
    from rifraf_tpu.ops import fused_pallas

    template, tlen, geom, w, bufs = _kernel_problem()
    outs = {}
    for enc in ("f32", "packed"):
        out = fused_pallas.fused_tables_auto(
            jnp.asarray(template), jnp.int32(tlen), bufs(enc), geom, w,
            16, 28, 4, want_stats=True, interpret=True, impl=impl,
            input_enc=enc,
        )
        outs[enc] = {k: np.asarray(v) for k, v in out.items()
                     if k != "impl"}
    f, p = outs["f32"], outs["packed"]
    for k in ("total", "scores", "sub", "ins", "del"):
        fin = np.isfinite(f[k]) & np.isfinite(p[k])
        d = (np.max(np.abs(f[k][fin] - p[k][fin])) if fin.any()
             else 0.0)
        assert d < 0.05, (k, d)
    np.testing.assert_array_equal(f["n_errors"], p["n_errors"])
    np.testing.assert_array_equal(f["edits"], p["edits"])


@pytest.mark.slow
def test_batch_aligner_packed_consensus_machinery(monkeypatch):
    """Engine-level gate (interpret): BatchAligner at input_enc="packed"
    agrees with the f32 aligner on totals, per-read scores, and the
    settled adaptive bandwidths — the quantization may shift scores
    within tolerance, never the algorithmic decisions on
    well-conditioned problems."""
    monkeypatch.setenv("RIFRAF_TPU_PALLAS_INTERPRET", "1")
    from rifraf_tpu.engine.realign import BatchAligner

    rng = np.random.default_rng(3)
    tlen = 24
    template = rng.integers(0, 4, tlen).astype(np.int8)
    reads = []
    for _ in range(4):
        slen = int(rng.integers(tlen - 4, tlen + 5))
        s = rng.integers(0, 4, slen).astype(np.int8)
        reads.append(
            make_read_scores(s, rng.uniform(-3.0, -1.0, slen), 5, SCORES)
        )
    for r in reads:
        r.bandwidth_fixed = True
    al_f = BatchAligner(reads, dtype=np.float32)
    al_f.realign(template, 0.1, want_stats=True)
    al_p = BatchAligner(reads, dtype=np.float32, input_enc="packed")
    al_p.realign(template, 0.1, want_stats=True)
    assert al_p._total == pytest.approx(al_f._total, abs=0.1)
    np.testing.assert_allclose(
        np.asarray(al_p.scores), np.asarray(al_f.scores),
        rtol=1e-3, atol=5e-2,
    )


# ---- fingerprints: --resume refuses to mix encodings -----------------------


def _clusters(seed=7):
    rng = np.random.default_rng(seed)
    out = []
    for tlen, n in ((20, 3), (24, 4)):
        reads = []
        for _ in range(n):
            slen = tlen + int(rng.integers(-2, 3))
            s = rng.integers(0, 4, slen).astype(np.int8)
            reads.append(
                make_read_scores(s, rng.uniform(-3.0, -1.0, slen), 5,
                                 SCORES)
            )
        out.append(reads)
    return out


def test_sweep_resume_refuses_mixed_encodings(tmp_path):
    """A journal written under the default encoding must not replay
    into a packed-configured run (and vice versa) — the encoding is
    part of the resume fingerprint when non-default, so pre-existing
    f32 journals stay valid."""
    from rifraf_tpu.io.journal import JournalError
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded

    clusters = _clusters()
    jp = str(tmp_path / "sweep.jsonl")
    sweep_clusters_sharded(clusters, max_iters=5, journal_path=jp)
    # same encoding resumes fine
    sweep_clusters_sharded(clusters, max_iters=5, journal_path=jp,
                           resume=True)
    with pytest.raises(JournalError):
        sweep_clusters_sharded(clusters, max_iters=5, journal_path=jp,
                               resume=True, input_enc="packed")


def test_sweep_stats_carry_input_enc():
    from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded

    clusters = _clusters(seed=9)
    res_f, st_f = sweep_clusters_sharded(clusters, max_iters=5,
                                         return_stats=True)
    res_p, st_p = sweep_clusters_sharded(clusters, max_iters=5,
                                         return_stats=True,
                                         input_enc="packed")
    assert st_f.input_enc == "f32" and st_p.input_enc == "packed"
    # the sweep's device programs are XLA (exact f32 inputs either
    # way): results are bit-identical across encodings here
    for a, b in zip(res_f, res_p):
        np.testing.assert_array_equal(a.consensus, b.consensus)
        assert a.score == b.score


def test_spool_fingerprint_keys_on_input_enc():
    from rifraf_tpu.cli.serve import _spool_fingerprint
    from rifraf_tpu.serve.request import ServeConfig

    args = types.SimpleNamespace(phred_cap=0, deadline_ms=0,
                                 max_iters=100,
                                 alignment_proposals=False)
    fp_f32 = _spool_fingerprint("/nonexistent/spool.jsonl", args,
                                ServeConfig())
    fp_pk = _spool_fingerprint("/nonexistent/spool.jsonl", args,
                               ServeConfig(input_enc="packed"))
    assert fp_f32 != fp_pk
    # the default folds NO encoding part in, so journals from before
    # the knob existed keep matching
    assert fp_f32 == _spool_fingerprint(
        "/nonexistent/spool.jsonl", args, ServeConfig(input_enc="f32")
    )


# ---- roofline: the byte model honors the encoding --------------------------


def test_roofline_packed_table_bytes_shrink():
    from rifraf_tpu.utils import roofline

    T1p, K, Npad, C = 1024, 64, 256, 128
    base = roofline.fused_mega_model(T1p, K, Npad, C)
    pk = roofline.fused_mega_model(T1p, K, Npad, C, input_enc="packed")
    # table term: 4 int8 planes + packed code words vs 5 f32 planes
    red = 1.0 - pk["tab_bytes"] / base["tab_bytes"]
    assert 0.75 < red < 0.82
    # non-table terms unchanged
    assert pk["band_bytes"] == base["band_bytes"]
    # both levers cut disjoint terms: combined reduction clears the
    # headline gate
    both = roofline.fused_mega_model(T1p, K, Npad, C, band_itemsize=2,
                                     input_enc="packed")
    assert 1.0 - both["bytes"] / base["bytes"] >= 0.20
