"""Supervised serving under injected faults (rifraf_tpu.serve.faults):
the fault plan itself, the degradation ladder, worker crash recovery,
crash-safe close(), bounded synchronous waits, and the no-hung-futures
invariant. Fast tests stay on the per-cluster fallback path
(batch_max_reads=1 — no batch-grid compiles); the batched-path fault
grid and the randomized chaos mix are marked slow."""

import threading
import time
from queue import Queue

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.parallel.cluster import PipelineJobError, pipeline_map
from rifraf_tpu.serve import (
    ConsensusServer,
    FaultPlan,
    InjectedFaultError,
    ServeConfig,
    ServerStats,
    ServerUnhealthyError,
    submit_many,
)
from rifraf_tpu.serve.faults import ENV_VAR, resolve_faults
from rifraf_tpu.serve.request import Request
from rifraf_tpu.serve.worker import STOP, Flush, Worker, resolve_future
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _cluster(nseqs=3, length=30, seed=0):
    rng = np.random.default_rng(seed)
    params = RifrafParams()
    _, _, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=nseqs, length=length, error_rate=0.02, rng=rng,
        seq_errors=SEQ_ERRORS,
    )
    return [
        make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                         params.bandwidth, params.scores)
        for s, p in zip(seqs, phreds)
    ]


def _ref_consensus(cluster):
    res = rifraf(
        [r.seq for r in cluster],
        error_log_ps=[r.error_log_p for r in cluster],
        params=RifrafParams(batch_size=0, batch_fixed=False),
    )
    return res.consensus


def _serve_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("rifraf-serve")]


def _fast_cfg(**kw):
    """Fallback-path config: no batch-grid compiles."""
    kw.setdefault("batch_max_reads", 1)
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("restart_backoff_s", 0.01)
    kw.setdefault("supervise_interval_s", 0.02)
    return ServeConfig(**kw)


def _mk_request(cluster, cfg, rid="t0"):
    from rifraf_tpu.parallel.sweep_sharded import bucket_key, cluster_info

    info = cluster_info(cluster)
    return Request(
        id=rid, cluster=list(cluster), info=info,
        key=bucket_key(info, cfg.read_bucket, cfg.band_bucket,
                       cfg.len_bucket),
        t_submit=time.perf_counter(), deadline=None,
    )


# ------------------------------------------------------------ fault plan


def test_fault_plan_parse():
    plan = FaultPlan.parse(
        "dispatch:error:n=2;fetch:delay:ms=50;pack:crash:after=3,p=0.5,"
        "seed=7"
    )
    d, f, p = plan.specs
    assert (d.site, d.kind, d.n) == ("dispatch", "error", 2)
    assert (f.kind, f.ms, f.n) == ("delay", 50.0, 1)
    assert (p.kind, p.after, p.p, p.seed) == ("crash", 3, 0.5, 7)
    assert bool(plan)
    assert not FaultPlan.parse("")
    assert not FaultPlan.parse(None)
    with pytest.raises(ValueError):
        FaultPlan.parse("nosite:error")
    with pytest.raises(ValueError):
        FaultPlan.parse("dispatch:nokind")
    with pytest.raises(ValueError):
        FaultPlan.parse("dispatch:error:bogus=1")


def test_fault_plan_fire_counting():
    plan = FaultPlan.parse("dispatch:error:n=2,after=1")
    plan.fire("dispatch")  # invocation 0: skipped by after=1
    with pytest.raises(InjectedFaultError):
        plan.fire("dispatch")
    with pytest.raises(InjectedFaultError):
        plan.fire("dispatch")
    plan.fire("dispatch")  # n=2 exhausted
    plan.fire("fetch")  # other sites unaffected
    snap = plan.snapshot()
    assert snap["site_calls"] == {"dispatch": 4, "fetch": 1}
    assert snap["specs"][0]["fired"] == 2


def test_fault_plan_bernoulli_deterministic():
    def fires(seed):
        plan = FaultPlan.parse(f"fetch:error:p=0.5,n=0,seed={seed}")
        out = []
        for _ in range(32):
            try:
                plan.fire("fetch")
                out.append(0)
            except InjectedFaultError:
                out.append(1)
        return out

    a, b = fires(3), fires(3)
    assert a == b  # same seed, same schedule
    assert 0 < sum(a) < 32  # actually probabilistic
    assert fires(4) != a  # seed changes the schedule


def test_fault_plan_delay_sleeps():
    plan = FaultPlan.parse("fetch:delay:ms=40")
    t0 = time.perf_counter()
    plan.fire("fetch")
    assert time.perf_counter() - t0 >= 0.04
    t0 = time.perf_counter()
    plan.fire("fetch")  # n=1 spent: no further delay
    assert time.perf_counter() - t0 < 0.04


def test_resolve_faults_env(monkeypatch):
    plan = FaultPlan.parse("admit:error")
    assert resolve_faults(plan) is plan
    assert resolve_faults("admit:error").specs[0].site == "admit"
    monkeypatch.setenv(ENV_VAR, "fetch:delay:ms=1")
    assert resolve_faults(None).specs[0].site == "fetch"
    monkeypatch.delenv(ENV_VAR)
    assert not resolve_faults(None)
    with pytest.raises(TypeError):
        resolve_faults(42)


# ------------------------------------------------- pipeline stage hook


def test_pipeline_stage_hook_called_per_stage():
    calls = []
    out = pipeline_map(
        lambda x: x, lambda x: x * 10, lambda x: x + 1, [1, 2],
        stage_hook=lambda stage, i: calls.append((stage, i)),
    )
    assert out == [11, 21]
    for stage in ("pack", "run", "collect"):
        assert [(stage, 0), (stage, 1)] == [c for c in calls
                                            if c[0] == stage]


def test_pipeline_stage_hook_error_isolates_job():
    def hook(stage, i):
        if stage == "run" and i == 0:
            raise RuntimeError("boom")

    out = pipeline_map(
        lambda x: x, lambda x: x, lambda x: x, [1, 2],
        on_error="return", stage_hook=hook,
    )
    assert isinstance(out[0], PipelineJobError)
    assert out[0].stage == "run"
    assert out[1] == 2


# ------------------------------------------------ future-resolution race


def test_double_resolve_is_counted_noop():
    stats = ServerStats()
    cfg = _fast_cfg()
    req = _mk_request(_cluster(), cfg)
    from rifraf_tpu.serve.request import Response

    assert resolve_future(req, Response(id="t0", ok=True), stats)
    assert not resolve_future(req, Response(id="t0", ok=False), stats)
    assert req.future.result().ok  # first resolver won
    assert stats.snapshot()["counters"]["double_resolve"] == 1


# --------------------------------------------------- worker loop hardening


def test_run_loop_stop_mid_burst_still_runs_collected():
    cfg = _fast_cfg(supervise=False)
    stats = ServerStats()
    w = Worker(cfg, stats)
    req = _mk_request(_cluster(), cfg)
    q = Queue()
    q.put(Flush("fallback", [req]))
    q.put(STOP)
    w.run_loop(q)  # synchronous: returns at STOP
    res = req.future.result(timeout=0)
    assert res.ok and res.path == "fallback"


def test_run_loop_survives_unexpected_exception():
    cfg = _fast_cfg(supervise=False, faults="fallback:error:n=1")
    stats = ServerStats()
    w = Worker(cfg, stats)

    def bomb(*a, **k):
        raise RuntimeError("ladder bookkeeping exploded")

    w._retry_or_fail = bomb  # escape per-job isolation on purpose
    r1 = _mk_request(_cluster(seed=1), cfg, "r1")
    r2 = _mk_request(_cluster(seed=2), cfg, "r2")
    q = Queue()
    q.put(Flush("fallback", [r1]))  # hits the injected fault -> bomb
    q.put(Flush("fallback", [r2]))  # same burst, runs clean
    q.put(STOP)
    w.run_loop(q)
    assert r2.future.result(timeout=0).ok
    res1 = r1.future.result(timeout=0)
    assert not res1.ok and res1.error.code == "internal"
    c = stats.snapshot()["counters"]
    assert c["worker_loop_errors"] == 1


# ------------------------------------------------------- ladder (fast path)


def test_transient_fallback_fault_recovers_bit_identical():
    clusters = [_cluster(seed=s) for s in range(3)]
    srv = ConsensusServer(_fast_cfg(faults="fallback:error:n=1"))
    out = submit_many(clusters, server=srv)
    srv.close()
    assert all(r.ok for r in out)
    for r, c in zip(out, clusters):
        assert np.array_equal(r.consensus, _ref_consensus(c))
    lad = srv.stats.ladder()
    assert lad["retry_fallback"] >= 1 and lad["recovered"] >= 1


def test_budget_exhaustion_fails_typed():
    srv = ConsensusServer(_fast_cfg(faults="fallback:error:n=0",
                                    max_retries=1))
    out = submit_many([_cluster()], server=srv)
    srv.close()
    assert not out[0].ok and out[0].error.code == "internal"
    assert srv.stats.ladder()["exhausted"] >= 1


# ----------------------------------------------------- crash supervision


def test_worker_crash_restart_recovers():
    clusters = [_cluster(seed=s) for s in range(3)]
    srv = ConsensusServer(_fast_cfg(faults="fallback:crash:n=1"))
    out = submit_many(clusters, server=srv)
    health = srv.health()
    srv.close()
    assert all(r.ok for r in out)
    for r, c in zip(out, clusters):
        assert np.array_equal(r.consensus, _ref_consensus(c))
    assert health["worker_restarts"] == 1
    assert health["worker_alive"]
    assert not _serve_threads()  # no leaked threads after close


def test_restart_cap_declares_unhealthy():
    srv = ConsensusServer(_fast_cfg(faults="fallback:crash:n=0",
                                    max_restarts=0))
    fut = srv.submit(_cluster())
    res = fut.result(timeout=30)
    assert not res.ok and res.error.code == "worker_crash"
    deadline = time.perf_counter() + 5.0
    while not srv.health()["unhealthy"]:
        assert time.perf_counter() < deadline
        time.sleep(0.01)
    with pytest.raises(ServerUnhealthyError):
        srv.submit(_cluster())
    srv.close()
    assert not _serve_threads()


def test_stall_watchdog_counts():
    srv = ConsensusServer(_fast_cfg(
        faults="fallback:delay:ms=400", stall_timeout_s=0.1,
    ))
    fut = srv.submit(_cluster())
    assert fut.result(timeout=30).ok  # the stall clears by itself
    counters = srv.stats.snapshot()["counters"]
    srv.close()
    assert counters.get("worker_stalls", 0) >= 1


def test_batcher_crash_restarts():
    srv = ConsensusServer(_fast_cfg())
    try:
        orig_due = srv._batcher.due
        state = {"armed": True}

        def due_once_broken(now):
            if state["armed"]:
                state["armed"] = False
                raise RuntimeError("batcher exploded")
            return orig_due(now)

        srv._batcher.due = due_once_broken
        # the first submit trips the bomb in the batcher loop (the
        # fallback-kind request itself is flushed before the bomb, so
        # it still lands); the supervisor then restarts the thread
        out = submit_many([_cluster()], server=srv)
        assert out[0].ok
        deadline = time.perf_counter() + 5.0
        while srv.health()["batcher_restarts"] < 1:
            assert time.perf_counter() < deadline
            time.sleep(0.01)
        assert srv.health()["batcher_alive"]
        # the restarted loop keeps serving
        out2 = submit_many([_cluster(seed=9)], server=srv)
        assert out2[0].ok
    finally:
        srv.close()


# ----------------------------------------------------- admission faults


def test_admit_fault_raises_to_caller():
    srv = ConsensusServer(_fast_cfg(faults="admit:error:n=1"),
                          start=False)
    with pytest.raises(InjectedFaultError):
        srv.submit(_cluster())
    srv.close()


# ---------------------------------------------------- crash-safe close


def test_close_resolves_inflight_futures():
    srv = ConsensusServer(_fast_cfg(faults="fallback:delay:ms=1200",
                                    max_wait_ms=200.0))
    futs = [srv.submit(_cluster(seed=s)) for s in range(3)]
    t0 = time.perf_counter()
    srv.close(timeout=0.3)
    # the drain deadline expires while the worker sits in the injected
    # delay: close returns promptly and every future is ALREADY
    # resolved typed — the wedged worker finishes in the background
    # (its late responses are double-resolve no-ops)
    assert time.perf_counter() - t0 < 3.0
    for f in futs:
        res = f.result(timeout=0)  # resolved, not hung
        assert not res.ok and res.error.code == "server_closed"
    for t in _serve_threads():
        t.join(timeout=30.0)
    assert not _serve_threads()


def test_close_unstarted_server_resolves_futures():
    srv = ConsensusServer(_fast_cfg(), start=False)
    fut = srv.submit(_cluster())
    srv.close()
    res = fut.result(timeout=0)
    assert not res.ok and res.error.code == "server_closed"


def test_submit_many_bounded_on_dead_worker():
    """A dead unsupervised worker must yield typed timeout responses,
    never hang submit_many."""
    cfg = _fast_cfg(supervise=False, faults="fallback:crash:n=0",
                    result_timeout_s=2.0)
    srv = ConsensusServer(cfg)
    t0 = time.perf_counter()
    out = submit_many([_cluster(seed=s) for s in range(2)], server=srv)
    wall = time.perf_counter() - t0
    srv.close(timeout=1.0)
    assert wall < 30.0
    assert all(not r.ok for r in out)
    assert {r.error.code for r in out} <= {"wait_timeout",
                                           "worker_crash", "internal"}


def test_snapshot_includes_health():
    srv = ConsensusServer(_fast_cfg(faults="fetch:delay:ms=1"))
    snap = srv.snapshot()
    srv.close()
    h = snap["health"]
    assert h["healthy"] and not h["closed"]
    assert h["batcher_alive"] and h["worker_alive"]
    assert "retry_ladder" in h and "last_flush_age_s" in h
    assert h["faults"]["specs"][0]["site"] == "fetch"
    import json

    json.dumps(snap)  # JSON-serializable as exported


# --------------------------------------------- batched-path grid (slow)


@pytest.mark.slow
@pytest.mark.parametrize("site", ["pack", "compile", "dispatch", "fetch"])
def test_batched_fault_grid_recovers_bit_identical(site):
    """A transient fault at each batched-path site: the ladder re-runs
    the micro-batch one rung down, every future resolves, and recovered
    results equal the unfaulted reference bit for bit."""
    clusters = [_cluster(seed=s) for s in range(4)]
    srv = ConsensusServer(ServeConfig(max_wait_ms=10.0,
                                      faults=f"{site}:error:n=1"))
    out = submit_many(clusters, server=srv)
    srv.close()
    assert all(r.ok for r in out)
    for r, c in zip(out, clusters):
        assert np.array_equal(r.consensus, _ref_consensus(c))
    lad = srv.stats.ladder()
    assert lad.get("retry_block", 0) + lad.get("retry_fallback", 0) >= 1
    assert not _serve_threads()


@pytest.mark.slow
def test_batched_double_fault_descends_to_fallback():
    """Two consecutive dispatch faults exhaust rungs 0 and 1; rung 2
    (per-request fallback) still recovers bit-identically."""
    clusters = [_cluster(seed=s) for s in range(4)]
    srv = ConsensusServer(ServeConfig(max_wait_ms=10.0,
                                      faults="dispatch:error:n=2"))
    out = submit_many(clusters, server=srv)
    srv.close()
    assert all(r.ok for r in out)
    for r, c in zip(out, clusters):
        assert np.array_equal(r.consensus, _ref_consensus(c))
    lad = srv.stats.ladder()
    assert lad["retry_block"] >= 1
    assert lad["retry_fallback"] >= 1
    assert lad["recovered"] >= len(clusters)


@pytest.mark.slow
def test_randomized_chaos_every_future_resolves():
    """Seeded Bernoulli faults across several sites at once: every
    request resolves (ok or typed), successes stay bit-identical, and
    no serve thread outlives close()."""
    clusters = [_cluster(seed=s) for s in range(8)]
    faults = ("pack:error:p=0.3,n=0,seed=5;"
              "dispatch:error:p=0.3,n=0,seed=6;"
              "fetch:delay:ms=10,p=0.5,n=0,seed=7")
    srv = ConsensusServer(ServeConfig(max_wait_ms=10.0, faults=faults,
                                      result_timeout_s=120.0))
    out = submit_many(clusters, server=srv)
    srv.close()
    assert len(out) == len(clusters)
    for r, c in zip(out, clusters):
        assert r.ok or r.error is not None  # typed, always
        if r.ok:
            assert np.array_equal(r.consensus, _ref_consensus(c))
    assert not _serve_threads()
