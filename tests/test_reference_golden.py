"""Reference-parity golden test against the Julia repo's shipped data.

Runs the CLI end-to-end on /root/reference/data/input-reads-{1,2}.fastq
with references.fasta and compares the consensus to the shipped
consensus-results.fasta. The reference checkout is not part of this
repo; when it is absent (CI, most dev containers) the whole module
skips — the test only bites on machines provisioned with the upstream
Rifraf.jl tree.
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REF_DATA = "/root/reference/data"


def _record_for(records, stem, idx):
    """Pick the record matching an input file stem, falling back to
    positional order (the shipped files pair 1:1 with the inputs)."""
    for name, seq in records:
        if stem in name:
            return name, seq
    if idx - 1 < len(records):
        return records[idx - 1]
    raise AssertionError(f"no record for {stem!r} in {len(records)} records")


@pytest.mark.parametrize("idx", [1, 2])
def test_cli_matches_shipped_consensus(idx, tmp_path):
    if not os.path.isdir(REF_DATA):
        pytest.skip("/root/reference checkout not present")
    from rifraf_tpu.cli.consensus import main
    from rifraf_tpu.io.fastx import read_fasta_records
    from rifraf_tpu.utils.constants import decode_seq, encode_seq

    reads = os.path.join(REF_DATA, f"input-reads-{idx}.fastq")
    refs = os.path.join(REF_DATA, "references.fasta")
    golden = os.path.join(REF_DATA, "consensus-results.fasta")
    for path in (reads, refs, golden):
        if not os.path.isfile(path):
            pytest.skip(f"{path} not present")

    stem = f"input-reads-{idx}"
    # the CLI uses the FIRST reference record unless given a map; pin
    # the matching record by writing a single-record reference file
    ref_name, ref_seq = _record_for(read_fasta_records(refs), stem, idx)
    one_ref = tmp_path / "reference.fasta"
    one_ref.write_text(f">{ref_name}\n{ref_seq}\n")

    out = tmp_path / "consensus.fasta"
    rc = main([
        "--reference", str(one_ref),
        "1,2,2",  # seq-errors: mismatch, insertion, deletion ratios
        reads,
        str(out),
    ])
    assert rc == 0

    got_records = read_fasta_records(str(out))
    assert len(got_records) == 1, "one input file -> one consensus"
    want_name, want_seq = _record_for(
        read_fasta_records(golden), stem, idx)
    got = np.asarray(encode_seq(got_records[0][1]))
    want = np.asarray(encode_seq(want_seq))
    assert decode_seq(got) == decode_seq(want), (
        f"consensus for {stem} differs from shipped {want_name}"
    )
