"""AOT executable persistence (serve.aot): entry keys, the
export/load round trip, failure degradation, and cache clearing.

The in-process tests wrap small jitted functions directly — the
protocol under test is the cache's, not the consensus programs'. The
fresh-process bit-identity smoke (a cold import of a warm process's
export) is marked slow; CI's elastic job runs it explicitly.
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rifraf_tpu.serve import aot
from rifraf_tpu.utils.cachedir import atomic_write_bytes


@pytest.fixture
def cache(tmp_path):
    """An activated AotCache in tmp_path; always deactivated after."""
    c = aot.activate(str(tmp_path / "aot"))
    yield c
    aot.deactivate()


def _entries(cache):
    out = []
    for root, _dirs, files in os.walk(cache.path):
        out += [os.path.join(root, f) for f in files
                if f.endswith(".jaxexp")]
    return sorted(out)


# ------------------------------------------------------------ keying


def test_avals_digest_separates_statics_shapes_dtypes():
    x32 = jnp.zeros((4,), jnp.float32)
    x16 = jnp.zeros((4,), jnp.bfloat16)
    y32 = jnp.zeros((8,), jnp.float32)
    base = aot._avals_digest("k", (1,), (x32,))
    assert base == aot._avals_digest("k", (1,), (x32,))
    assert base != aot._avals_digest("k", (2,), (x32,))  # statics
    assert base != aot._avals_digest("k2", (1,), (x32,))  # kind
    assert base != aot._avals_digest("k", (1,), (x16,))  # dtype
    assert base != aot._avals_digest("k", (1,), (y32,))  # shape
    assert base != aot._avals_digest("k", (1,), (x32, x32))  # tree


def test_resolve_aot_dir(monkeypatch, tmp_path):
    monkeypatch.delenv("RIFRAF_TPU_AOT_CACHE", raising=False)
    assert aot.resolve_aot_dir(None) is None
    assert aot.resolve_aot_dir("") is None
    assert aot.resolve_aot_dir("off") is None
    assert aot.resolve_aot_dir(str(tmp_path)) == str(tmp_path)
    assert "rifraf_tpu_aot" in aot.resolve_aot_dir("default")
    monkeypatch.setenv("RIFRAF_TPU_AOT_CACHE", str(tmp_path))
    assert aot.resolve_aot_dir(None) == str(tmp_path)
    monkeypatch.setenv("RIFRAF_TPU_AOT_CACHE", "off")
    assert aot.resolve_aot_dir(None) is None


# ---------------------------------------------------- the round trip


def test_program_passthrough_without_cache():
    aot.deactivate()
    calls = []

    @jax.jit
    def f(x):
        calls.append(1)
        return x * 2

    prog = aot.aot_program("t", (), f)
    x = jnp.arange(4.0)
    np.testing.assert_array_equal(prog(x), f(x))
    assert aot.active_cache() is None


def test_export_then_reload_bit_identical(cache):
    @jax.jit
    def f(x):
        # a while_loop, like the real programs: exercises exportability
        # beyond straight-line arithmetic
        def body(c):
            i, v = c
            return i + 1, v * 1.5 + 0.25

        return jax.lax.while_loop(lambda c: c[0] < 7, body, (0, x))[1]

    prog = aot.aot_program("t", (7,), f)
    x = jnp.linspace(-1.0, 1.0, 16)
    want = np.asarray(f(x))

    got = np.asarray(prog(x))  # miss: runs the exported form
    np.testing.assert_array_equal(got, want)
    snap = cache.snapshot()
    assert snap["aot_misses"] == 1
    assert snap["aot_exports"] == 1
    assert len(_entries(cache)) == 1

    # a fresh cache object over the same directory = a cold process:
    # the entry loads from disk, no re-export, and the result is
    # bit-identical
    aot.deactivate()
    cold = aot.activate(cache.path)
    prog2 = aot.aot_program("t", (7,), f)
    np.testing.assert_array_equal(np.asarray(prog2(x)), want)
    snap = cold.snapshot()
    assert snap["aot_loads"] == 1
    assert snap["aot_exports"] == 0
    assert snap["aot_misses"] == 0


def test_second_call_uses_loaded_entry_not_reexport(cache):
    @jax.jit
    def f(x):
        return x + 1

    prog = aot.aot_program("t", (), f)
    x = jnp.zeros((3,))
    prog(x)
    prog(x)
    snap = cache.snapshot()
    assert snap["aot_misses"] == 1
    assert snap["aot_exports"] == 1


def test_distinct_shapes_get_distinct_entries(cache):
    @jax.jit
    def f(x):
        return x + 1

    prog = aot.aot_program("t", (), f)
    prog(jnp.zeros((3,)))
    prog(jnp.zeros((5,)))
    assert len(_entries(cache)) == 2
    assert cache.snapshot()["aot_exports"] == 2


# ------------------------------------------------ failure degradation


def test_corrupt_payload_degrades_to_warm_miss(cache):
    @jax.jit
    def f(x):
        return x * 3

    prog = aot.aot_program("t", (), f)
    x = jnp.ones((4,))
    want = np.asarray(f(x))
    prog(x)
    (path,) = _entries(cache)

    atomic_write_bytes(path, b"not a serialized module")
    aot.deactivate()
    cold = aot.activate(cache.path)
    prog2 = aot.aot_program("t", (), f)
    # the load fails, is counted, and the traced original answers
    np.testing.assert_array_equal(np.asarray(prog2(x)), want)
    snap = cold.snapshot()
    assert snap["aot_load_errors"] == 1
    assert snap["aot_loads"] == 0
    # pinned bad: a second call does not retry the load or re-export
    prog2(x)
    assert cold.snapshot()["aot_load_errors"] == 1
    assert cold.snapshot()["aot_exports"] == 0


def test_export_failure_counts_and_serves(cache):
    class Unexportable:
        """Not a jitted callable: jax.export rejects it, the wrapper
        must serve through the original anyway."""

        def __call__(self, x):
            return jnp.asarray(x) + 5

    prog = aot.aot_program("t", (), Unexportable())
    x = jnp.zeros((2,))
    np.testing.assert_array_equal(np.asarray(prog(x)),
                                  np.asarray(x + 5))
    snap = cache.snapshot()
    assert snap["aot_export_errors"] == 1
    assert len(_entries(cache)) == 0
    # the failed digest is pinned: no repeated export attempts
    prog(x)
    assert cache.snapshot()["aot_export_errors"] == 1


# -------------------------------------------------------- clearing


def test_clear_aot_cache_drops_entries_and_reexports(cache):
    @jax.jit
    def f(x):
        return x - 2

    prog = aot.aot_program("t", (), f)
    x = jnp.zeros((3,))
    prog(x)
    assert len(_entries(cache)) == 1
    n = aot.clear_aot_cache()
    assert n >= 1
    assert len(_entries(cache)) == 0
    # cleared entries re-export on next first-sight (fresh cache)
    aot.deactivate()
    aot.activate(cache.path)
    prog2 = aot.aot_program("t", (), f)
    prog2(x)
    assert len(_entries(cache)) == 1


def test_recover_stale_cache_clears_aot(tmp_path, monkeypatch):
    """The PR-8 stale-libtpu recovery path clears the persisted AOT
    entries along with the XLA compilation cache."""
    from rifraf_tpu.engine import driver

    # recovery disables the process-wide compilation cache; restore it
    # afterwards so the rest of the pytest process keeps its conftest
    # cache behavior
    prior_enabled = jax.config.jax_enable_compilation_cache
    c = aot.activate(str(tmp_path / "aot"))
    try:

        @jax.jit
        def f(x):
            return x + 9

        aot.aot_program("t", (), f)(jnp.zeros((2,)))
        assert len(_entries(c)) == 1
        stale = RuntimeError(
            "FAILED_PRECONDITION: libtpu version mismatch")
        assert driver.recover_stale_cache(stale)
        assert len(_entries(c)) == 0
        # a non-stale error must not touch the cache
        aot.aot_program("t2", (), f)(jnp.zeros((2,)))
        assert not driver.recover_stale_cache(
            RuntimeError("INVALID_ARGUMENT: shape mismatch"))
        assert len(_entries(c)) == 1
    finally:
        aot.deactivate()
        jax.config.update("jax_enable_compilation_cache",
                          prior_enabled)


# ----------------------------------------- fresh-process smoke (slow)


_CHILD = r"""
import sys
import jax, jax.numpy as jnp
import numpy as np
from rifraf_tpu.serve import aot

mode, cache_dir = sys.argv[1], sys.argv[2]

@jax.jit
def f(x):
    def body(c):
        i, v = c
        return i + 1, v * 1.125 + 0.03125
    return jax.lax.while_loop(lambda c: c[0] < 9, body, (0, x))[1]

x = jnp.linspace(-2.0, 2.0, 32)
if mode == "warm":
    aot.activate(cache_dir)
    out = aot.aot_program("t", (9,), f)(x)
else:  # cold
    cache = aot.activate(cache_dir)
    out = aot.aot_program("t", (9,), f)(x)
    snap = cache.snapshot()
    assert snap["aot_loads"] == 1, snap
    assert snap["aot_exports"] == 0, snap
np.save(sys.argv[3], np.asarray(out))
"""


@pytest.mark.slow
def test_fresh_process_import_bit_identity(tmp_path):
    """The CI round-trip contract: a warm process exports, a FRESH
    process (cold import — no tracing of the original) loads the entry
    and produces bit-identical output."""
    cache_dir = str(tmp_path / "aot")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    outs = {}
    for mode in ("warm", "cold"):
        out = str(tmp_path / f"{mode}.npy")
        subprocess.run(
            [sys.executable, "-c", _CHILD, mode, cache_dir, out],
            check=True, env=env, timeout=300)
        outs[mode] = np.load(out)
    np.testing.assert_array_equal(outs["warm"], outs["cold"])
