"""Tests for L0-L2: phred conversions, error model, read score precompute.

Oracles from /root/reference/test/test_rifrafsequences.jl and the reference
source semantics.
"""

import numpy as np
import pytest

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import (
    batch_reads,
    empty_read_scores,
    make_read_scores,
    read_scores_from_phreds,
)
from rifraf_tpu.utils import (
    cap_phreds,
    decode_seq,
    encode_seq,
    logsumexp10,
    p_to_phred,
    phred_to_log_p,
    phred_to_p,
    summax,
)


def test_encode_decode():
    assert decode_seq(encode_seq("ACGT")) == "ACGT"
    assert decode_seq(encode_seq("")) == ""
    np.testing.assert_array_equal(encode_seq("AACGT"), [0, 0, 1, 2, 3])
    with pytest.raises(ValueError):
        encode_seq("ACGX")


def test_phred_roundtrip():
    phreds = np.array([1, 10, 30, 93], dtype=np.int8)
    log_p = phred_to_log_p(phreds)
    np.testing.assert_allclose(log_p, phreds / -10.0)
    p = phred_to_p(phreds)
    np.testing.assert_allclose(p, 10.0 ** (phreds / -10.0))
    back = p_to_phred(p)
    np.testing.assert_array_equal(back, phreds)


def test_p_to_phred_caps():
    assert p_to_phred(np.array([1e-30]))[0] == 93


def test_cap_phreds():
    np.testing.assert_array_equal(
        cap_phreds(np.array([1, 50, 93], dtype=np.int8), 30), [1, 30, 30]
    )
    with pytest.raises(ValueError):
        cap_phreds(np.array([1], dtype=np.int8), 0)


def test_logsumexp10():
    x = np.array([-1.0, -2.0, -3.0])
    expected = np.log10(np.sum(10.0**x))
    assert abs(logsumexp10(x) - expected) < 1e-12
    assert logsumexp10([]) == -np.inf


def test_summax():
    a = np.array([1.0, 2.0, 3.0])
    b = np.array([3.0, 1.0, 0.5])
    assert summax(a, b) == 4.0
    # uses min length, like the reference
    assert summax(a[:2], b) == 4.0


def test_error_model_normalize():
    em = ErrorModel(8, 0, 0, 1, 1).normalize()
    assert abs(em.mismatch - 0.8) < 1e-12
    assert abs(em.codon_insertion - 0.1) < 1e-12


def test_scores_from_error_model():
    # codon indel extra penalty is 3x the single indel extra
    # (errormodel.jl:75-80)
    s = Scores.from_error_model(
        ErrorModel(1.0, 1.0, 1.0, 1.0, 1.0), mismatch=-0.5, insertion=-1.0, deletion=-2.0
    )
    base = np.log10(0.2)
    assert abs(s.mismatch - (base - 0.5)) < 1e-12
    assert abs(s.insertion - (base - 1.0)) < 1e-12
    assert abs(s.deletion - (base - 2.0)) < 1e-12
    assert abs(s.codon_insertion - (base - 3.0)) < 1e-12
    assert abs(s.codon_deletion - (base - 6.0)) < 1e-12


def test_scores_no_codon():
    s = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0))
    assert s.codon_insertion == -np.inf
    assert s.codon_deletion == -np.inf


class TestReadScores:
    # oracle: test_rifrafsequences.jl:15-28
    def test_score_vectors(self):
        error_log_p = np.array([-1.0, -2.0, -3.0, -4.0])
        scores = Scores(-1.0, -2.0, -3.0, -4.0, -5.0)
        rseq = make_read_scores("ACGT", error_log_p, 10, scores)

        np.testing.assert_allclose(
            rseq.match_scores, np.log10(1.0 - 10.0**error_log_p)
        )
        np.testing.assert_allclose(rseq.mismatch_scores, error_log_p + scores.mismatch)
        np.testing.assert_allclose(rseq.ins_scores, error_log_p + scores.insertion)
        np.testing.assert_allclose(
            rseq.del_scores, np.array([-1.0, -1.0, -2.0, -3.0, -4.0]) + scores.deletion
        )
        np.testing.assert_allclose(
            rseq.codon_ins_scores, np.array([-1.0, -2.0]) + scores.codon_insertion
        )
        np.testing.assert_allclose(
            rseq.codon_del_scores,
            np.array([-1.0, -1.0, -2.0, -3.0, -4.0]) + scores.codon_deletion,
        )
        assert abs(rseq.est_n_errors - np.sum(10.0**error_log_p)) < 1e-12

    def test_empty(self):
        scores = Scores(-1.0, -2.0, -3.0, -4.0, -5.0)
        rseq = make_read_scores("", [], 10, scores)
        assert len(rseq) == 0
        assert len(empty_read_scores(scores)) == 0

    def test_no_codon_scores(self):
        scores = Scores(-1.0, -2.0, -3.0)
        rseq = make_read_scores("ACGT", [-1.0, -2.0, -3.0, -4.0], 10, scores)
        assert rseq.codon_ins_scores is None
        assert rseq.codon_del_scores is None
        assert not rseq.do_codon_moves

    # oracle: test_rifrafsequences.jl:41-51
    def test_update_scores(self):
        scores = Scores(-1.0, -2.0, -3.0, -4.0, -5.0)
        rseq = make_read_scores("ACGT", [-1.0, -2.0, -3.0, -4.0], 10, scores)
        new_rseq = rseq.with_scores(Scores(-1.0, -1.0, -1.0, -1.0, -1.0))
        np.testing.assert_allclose(new_rseq.ins_scores, new_rseq.mismatch_scores)

    def test_phred_ctor(self):
        scores = Scores(-1.0, -2.0, -3.0)
        rseq = read_scores_from_phreds("ACGT", np.array([3, 50, 10, 70], dtype=np.int8), 10, scores)
        np.testing.assert_allclose(rseq.error_log_p, np.array([3, 50, 10, 70]) / -10.0)

    def test_validation(self):
        scores = Scores(-1.0, -2.0, -3.0)
        with pytest.raises(ValueError):
            make_read_scores("ACGT", [-1.0, -2.0], 10, scores)
        with pytest.raises(ValueError):
            make_read_scores("ACGT", [-1.0, -2.0, -3.0, -np.inf], 10, scores)
        with pytest.raises(ValueError):
            make_read_scores("ACGT", [-1.0, -2.0, -3.0, 0.5], 10, scores)
        with pytest.raises(ValueError):
            make_read_scores("ACGT", [-1.0] * 4, 0, scores)

    def test_reversed(self):
        scores = Scores(-1.0, -2.0, -3.0, -4.0, -5.0)
        rseq = make_read_scores("ACGT", [-1.0, -2.0, -3.0, -4.0], 10, scores)
        rev = rseq.reversed()
        np.testing.assert_array_equal(rev.seq, rseq.seq[::-1])
        np.testing.assert_allclose(rev.del_scores, rseq.del_scores[::-1])
        np.testing.assert_allclose(rev.codon_ins_scores, rseq.codon_ins_scores[::-1])


def test_batch_reads():
    scores = Scores(-1.0, -2.0, -3.0)
    r1 = make_read_scores("ACGT", [-1.0, -2.0, -3.0, -4.0], 9, scores)
    r2 = make_read_scores("AC", [-1.0, -2.0], 9, scores)
    batch = batch_reads([r1, r2], dtype=np.float64)
    assert batch.n_reads == 2
    assert batch.max_len == 4
    np.testing.assert_array_equal(batch.lengths, [4, 2])
    np.testing.assert_array_equal(batch.seq[1], [0, 1, -1, -1])
    np.testing.assert_allclose(batch.dels[1, :3], r2.del_scores)
    # codon scores disabled -> -inf
    assert np.all(np.isneginf(batch.cins))


def test_batch_reads_codon_plane_sentinel():
    """When NO read carries codon scores (the standard read path), the
    batch keeps a compact [N, 1] -inf sentinel instead of dead
    full-width codon planes; any codon-scored read restores the full
    [N, L(+1)] planes."""
    plain = Scores(-1.0, -2.0, -3.0)
    r1 = make_read_scores("ACGTACG", np.full(7, -1.5), 9, plain)
    r2 = make_read_scores("ACGT", np.full(4, -1.5), 9, plain)
    b = batch_reads([r1, r2], dtype=np.float64)
    assert not b.do_codon_moves
    assert b.cins.shape == (2, 1) and b.cdel.shape == (2, 1)
    assert np.all(np.isneginf(b.cins)) and np.all(np.isneginf(b.cdel))

    codon = Scores(-1.0, -2.0, -3.0, -4.0, -5.0)
    r3 = make_read_scores("ACGTACG", np.full(7, -1.5), 9, codon)
    b2 = batch_reads([r1, r3], dtype=np.float64)
    assert b2.do_codon_moves
    L = b2.max_len
    assert b2.cins.shape == (2, L) and b2.cdel.shape == (2, L + 1)
    np.testing.assert_allclose(b2.cins[1, : len(r3) - 2],
                               r3.codon_ins_scores)
    # the codon-free read's rows stay fully disabled
    assert np.all(np.isneginf(b2.cins[0]))


def test_reverse_complement():
    from rifraf_tpu.utils.constants import reverse_complement

    s = encode_seq("ACGTTG")
    assert decode_seq(reverse_complement(s)) == "CAACGT"
    # involution
    np.testing.assert_array_equal(reverse_complement(reverse_complement(s)), s)
    # padding codes survive untouched
    padded = np.array([0, 1, -1, 3], dtype=np.int8)
    out = reverse_complement(padded)
    np.testing.assert_array_equal(out, np.array([0, -1, 2, 3], dtype=np.int8))
