"""Production-path equality for the Pallas realign engine (interpret).

RIFRAF_TPU_PALLAS_INTERPRET=1 makes BatchAligner.pallas_eligible accept
the CPU backend and runs every Pallas kernel in interpret mode, so the
exact production wiring — packed-fetch layout, stats realigns with
in-kernel move recording, SCORE-stage move fetches + host traceback,
adaptation rounds on fill_stats_pallas, and the shard_map mesh variant —
is exercised through BatchAligner.realign and compared against the XLA
engine on identical problems. (Whole-driver interpret runs cost minutes
per hill-climb; the driver logic above the aligner is backend-agnostic
and pinned by the XLA-vs-numpy oracle suites.)
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from rifraf_tpu.engine.realign import BatchAligner
from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import make_read_scores

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))


def _reads(n=4, tlen=24, seed=3, bw=5, fixed=True):
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n):
        slen = int(rng.integers(tlen - 5, tlen + 6))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        reads.append(
            make_read_scores(s, rng.uniform(-3.0, -1.0, size=slen), bw, SCORES)
        )
    for r in reads:
        r.bandwidth_fixed = fixed
    return template, reads


def _assert_aligners_agree(al_p, al_x, stats: bool, tlen: int):
    assert al_p._total == pytest.approx(al_x._total, rel=1e-5, abs=1e-4)
    # the mesh aligner keeps its mesh-padding duplicate reads' scores;
    # compare the real-read prefix
    n = min(len(al_p.reads), len(al_x.reads))
    np.testing.assert_allclose(
        np.asarray(al_p.scores)[:n], np.asarray(al_x.scores)[:n],
        rtol=1e-5, atol=1e-4,
    )
    # valid rows: sub/del cover positions [0, tlen), ins [0, tlen]
    for a, b, hi, name in zip(
        al_p._tables_host, al_x._tables_host,
        (tlen, tlen + 1, tlen), ("sub", "ins", "del"),
    ):
        a, b = np.asarray(a)[:hi], np.asarray(b)[:hi]
        m = np.isfinite(b) & (b > -1e30)
        np.testing.assert_allclose(
            a[m], b[m], rtol=2e-4, atol=2e-4, err_msg=name
        )
        assert (a[~m] < -1e28).all(), name
    if stats:
        np.testing.assert_array_equal(al_p.edits_seen, al_x.edits_seen)


@pytest.mark.slow
def test_realign_stats_pallas_matches_xla(monkeypatch):
    """want_stats realign (the reference-default candidate machinery):
    in-kernel moves + device stats == the XLA stats components."""
    monkeypatch.setenv("RIFRAF_TPU_PALLAS_INTERPRET", "1")
    template, reads = _reads()
    al_p = BatchAligner(reads, dtype=np.float32)
    al_p.realign(template, 0.1, want_stats=True)
    al_x = BatchAligner(reads, dtype=np.float32, backend="xla")
    al_x.realign(template, 0.1, want_stats=True)
    _assert_aligners_agree(al_p, al_x, stats=True, tlen=len(template))


def _path_score(moves, read, template):
    """Score of a traceback path under the read's score vectors — the DP
    objective itself (align.jl:50-112, no trim/skew)."""
    i = j = total = 0
    for m in moves:
        if m == 1:  # match
            i += 1
            j += 1
            total += (
                read.match_scores[i - 1]
                if read.seq[i - 1] == template[j - 1]
                else read.mismatch_scores[i - 1]
            )
        elif m == 2:  # insert
            i += 1
            total += read.ins_scores[i - 1]
        else:  # delete
            j += 1
            total += read.del_scores[i]
    assert i == len(read) and j == len(template)
    return total


@pytest.mark.slow
def test_realign_moves_pallas_matches_xla(monkeypatch):
    """want_moves realign (SCORE stage): the uniform-frame move fetch +
    host traceback walk yields complete optimal paths. The two engines
    order the insert-chain G-sums differently, so exact-tie cells can
    legitimately break toward different (equally optimal) moves — each
    path must reproduce ITS OWN engine's score, and the scores must
    agree."""
    monkeypatch.setenv("RIFRAF_TPU_PALLAS_INTERPRET", "1")
    template, reads = _reads(seed=9)
    al_p = BatchAligner(reads, dtype=np.float32)
    al_p.realign(template, 0.1, want_moves=True)
    al_x = BatchAligner(reads, dtype=np.float32, backend="xla")
    al_x.realign(template, 0.1, want_moves=True)
    _assert_aligners_agree(al_p, al_x, stats=False, tlen=len(template))
    assert len(al_p.tracebacks) == len(reads)
    for k, read in enumerate(reads):
        sp = _path_score(al_p.tracebacks[k], read, template)
        sx = _path_score(al_x.tracebacks[k], read, template)
        assert sp == pytest.approx(float(al_p.scores[k]), abs=1e-3)
        assert sx == pytest.approx(float(np.asarray(al_x.scores)[k]), abs=1e-3)


@pytest.mark.slow
def test_realign_adaptation_pallas_matches_xla(monkeypatch):
    """Unsettled bandwidths: the fill_stats_pallas adaptation rounds
    must settle to the same per-read bandwidths as the XLA rounds."""
    monkeypatch.setenv("RIFRAF_TPU_PALLAS_INTERPRET", "1")
    # low starting bandwidth + long reads forces at least one doubling
    template, reads = _reads(n=3, tlen=32, seed=5, bw=2, fixed=False)
    al_p = BatchAligner(reads, dtype=np.float32)
    al_p.realign(template, 0.1)
    template2, reads2 = _reads(n=3, tlen=32, seed=5, bw=2, fixed=False)
    al_x = BatchAligner(reads2, dtype=np.float32, backend="xla")
    al_x.realign(template2, 0.1)
    np.testing.assert_array_equal(al_p.bandwidths, al_x.bandwidths)
    np.testing.assert_array_equal(al_p.fixed, al_x.fixed)
    _assert_aligners_agree(al_p, al_x, stats=False, tlen=len(template))


@pytest.mark.slow
def test_realign_mesh_pallas_matches_single(monkeypatch):
    """The shard_map mesh variant (8 virtual devices) must agree with
    the single-device XLA aligner — the multi-chip north-star realign
    on the fast engine."""
    from rifraf_tpu.parallel.sharding import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    monkeypatch.setenv("RIFRAF_TPU_PALLAS_INTERPRET", "1")
    template, reads = _reads(n=6, tlen=24, seed=7)
    mesh = make_mesh(8)
    al_p = BatchAligner(reads, dtype=np.float32, mesh=mesh)
    assert al_p.pallas_eligible(len(template))
    al_p.realign(template, 0.1, want_stats=True)
    al_x = BatchAligner(reads, dtype=np.float32, backend="xla")
    al_x.realign(template, 0.1, want_stats=True)
    # scores/tables vs the XLA engine (fp tolerance; exact-tie cells can
    # break toward different equally-optimal paths across engines, so
    # the discrete edit indicators are compared against the SINGLE-
    # DEVICE Pallas engine instead — identical per-lane arithmetic)
    _assert_aligners_agree(al_p, al_x, stats=False, tlen=len(template))
    al_s = BatchAligner(reads, dtype=np.float32)
    al_s.realign(template, 0.1, want_stats=True)
    np.testing.assert_array_equal(al_p.edits_seen, al_s.edits_seen)


def test_backend_pallas_unavailable_off_tpu(monkeypatch):
    """An explicit backend='pallas' must fail loudly off-TPU (without
    the interpret test hook) — never silently fall back to XLA."""
    monkeypatch.delenv("RIFRAF_TPU_PALLAS_INTERPRET", raising=False)
    template, reads = _reads(n=2, tlen=16)
    with pytest.raises(ValueError, match="pallas"):
        BatchAligner(reads, dtype=np.float32, backend="pallas")
