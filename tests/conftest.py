"""Test configuration.

Tests run on CPU with 8 virtual devices (for sharding tests) and x64 enabled
(the reference engine is Float64; exactness oracles compare at tight
tolerances).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# force CPU regardless of ambient JAX_PLATFORMS (the env var can be
# overridden by the harness; the config option always wins)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


# persistent compilation cache: the engine's bucketed shapes mean a small,
# stable set of executables — reuse them across test runs. Overridable so
# concurrent pytest processes can use private caches; RIFRAF_TPU_CACHE=off
# disables it (the jax cache serializer has segfaulted mid-suite on this
# image — see the machine-fingerprint note above).
from rifraf_tpu.utils.cachedir import machine_cache_dir  # noqa: E402

_cache = os.environ.get(
    "RIFRAF_TPU_CACHE", machine_cache_dir("/tmp/rifraf_jax_cache")
)
if _cache and _cache != "off":
    # one cache dir per xdist worker: the jax cache serializer has
    # segfaulted under concurrent writers on this image. (The suite
    # runs under xdist by default — see pytest.ini — both for wall
    # time and because XLA:CPU's compiler has segfaulted after a few
    # hundred compilations accumulate in ONE process; splitting the
    # suite across worker processes keeps every process under the
    # threshold.)
    _worker = os.environ.get("PYTEST_XDIST_WORKER")
    if _worker:
        _cache = f"{_cache}_{_worker}"
    jax.config.update("jax_compilation_cache_dir", _cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
