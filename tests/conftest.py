"""Test configuration.

Tests run on CPU with 8 virtual devices (for sharding tests) and x64 enabled
(the reference engine is Float64; exactness oracles compare at tight
tolerances).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# force CPU regardless of ambient JAX_PLATFORMS (the env var can be
# overridden by the harness; the config option always wins)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
# persistent compilation cache: the engine's bucketed shapes mean a small,
# stable set of executables — reuse them across test runs. Overridable so
# concurrent pytest processes can use private caches (the jax cache
# serializer has segfaulted under concurrent writers on this image).
jax.config.update(
    "jax_compilation_cache_dir",
    os.environ.get("RIFRAF_TPU_CACHE", "/tmp/rifraf_jax_cache"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
