"""Cluster-sharded sweep vs per-cluster driver equality (on the
8-virtual-device CPU mesh the conftest provides)."""

import numpy as np
import pytest

# whole-sweep executables are the most expensive compiles in the tree (x64 CPU compile dominates on 1-core hosts)
pytestmark = pytest.mark.slow

jax = pytest.importorskip("jax")

from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.parallel.sharding import make_mesh
from rifraf_tpu.parallel.sweep_sharded import sweep_clusters_sharded
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _clusters(n_clusters, nseqs=6, length=70, seed=0):
    rng = np.random.default_rng(seed)
    out, templates = [], []
    params = RifrafParams()
    for _ in range(n_clusters):
        _, template, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=nseqs, length=length, error_rate=0.03, rng=rng,
            seq_errors=SEQ_ERRORS,
        )
        reads = [
            make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                             params.bandwidth, params.scores)
            for s, p in zip(seqs, phreds)
        ]
        out.append(reads)
        templates.append(template)
    return out, templates


@pytest.mark.parametrize("use_mesh", [False, True])
def test_sweep_matches_per_cluster_driver(use_mesh):
    """Each cluster's sweep result must equal the per-cluster rifraf()
    run in the device-loop configuration (consensus, score, iterations,
    convergence) — sharded over the virtual mesh or unsharded."""
    clusters, templates = _clusters(5)
    mesh = make_mesh(8) if use_mesh else None
    res = sweep_clusters_sharded(clusters, mesh=mesh)
    assert len(res) == 5

    for g, reads in enumerate(clusters):
        seqs = [r.seq for r in reads]
        log_ps = [r.error_log_p for r in reads]
        ref = rifraf(
            seqs, error_log_ps=log_ps,
            params=RifrafParams(batch_size=0, batch_fixed=False,
                                do_alignment_proposals=False,
                                device_loop="on"),
        )
        assert np.array_equal(res[g].consensus, ref.consensus), g
        assert np.isclose(res[g].score, ref.state.score, rtol=1e-6), g
        assert res[g].n_iters == int(ref.state.stage_iterations.sum()), g
        assert res[g].converged == ref.state.converged, g


@pytest.mark.parametrize("scheduler", ["bucketed", "uniform"])
def test_sweep_uneven_clusters(scheduler):
    """Ragged cluster sizes and read lengths pad cleanly under both
    schedulers."""
    clusters, templates = _clusters(3, seed=5)
    clusters[1] = clusters[1][:4]  # fewer reads
    res = sweep_clusters_sharded(clusters, mesh=make_mesh(8),
                                 scheduler=scheduler)
    for g, r in enumerate(res):
        seqs = [x.seq for x in clusters[g]]
        log_ps = [x.error_log_p for x in clusters[g]]
        ref = rifraf(
            seqs, error_log_ps=log_ps,
            params=RifrafParams(batch_size=0, batch_fixed=False,
                                do_alignment_proposals=False,
                                device_loop="on"),
        )
        assert np.array_equal(r.consensus, ref.consensus), g


def test_sweep_shuffled_inputs_restore_order():
    """Heterogeneous clusters landing in different shape buckets, fed in
    shuffled order: results come back in INPUT order, each bit-identical
    to the per-cluster driver. lane_target=0 keeps the buckets distinct
    (the lane-packing coalescer would merge these tile-underfilled
    buckets into one launch — tests/test_lane_packing.py covers that
    packed path)."""
    rng = np.random.default_rng(11)
    pool = []
    for nseqs, length, seed in [(4, 50, 1), (8, 90, 2), (5, 50, 3),
                                (8, 92, 4), (4, 52, 5)]:
        c, _ = _clusters(1, nseqs=nseqs, length=length, seed=seed)
        pool.append(c[0])
    shuffled = [pool[i] for i in rng.permutation(len(pool))]
    res, stats = sweep_clusters_sharded(shuffled, return_stats=True,
                                        lane_target=0)
    assert stats.n_buckets > 1  # the permutation spans buckets
    assert len(res) == len(shuffled)
    for g, reads in enumerate(shuffled):
        ref = rifraf(
            [r.seq for r in reads],
            error_log_ps=[r.error_log_p for r in reads],
            params=RifrafParams(batch_size=0, batch_fixed=False,
                                do_alignment_proposals=False,
                                device_loop="on"),
        )
        assert np.array_equal(res[g].consensus, ref.consensus), g
        assert np.isclose(res[g].score, ref.state.score, rtol=1e-6), g


def test_sweep_alignment_proposals_matches_driver():
    """do_alignment_proposals=True sweep scope: the in-kernel edits gate
    under the cluster vmap must reproduce the per-cluster driver run in
    the same configuration."""
    clusters, _ = _clusters(3, seed=9)
    res = sweep_clusters_sharded(clusters, do_alignment_proposals=True)
    for g, reads in enumerate(clusters):
        ref = rifraf(
            [r.seq for r in reads],
            error_log_ps=[r.error_log_p for r in reads],
            params=RifrafParams(batch_size=0, batch_fixed=False,
                                do_alignment_proposals=True,
                                device_loop="on"),
        )
        assert np.array_equal(res[g].consensus, ref.consensus), g
        assert np.isclose(res[g].score, ref.state.score, rtol=1e-6), g
        assert res[g].n_iters == int(ref.state.stage_iterations.sum()), g
        assert res[g].converged == ref.state.converged, g


def test_sweep_chunked_matches_unchunked():
    """Pinned chunk shapes: a chunked sweep is bit-identical to the
    unchunked one (same bucket grid, tail chunks padded to the same
    cluster count)."""
    clusters, _ = _clusters(5, seed=13)
    whole = sweep_clusters_sharded(clusters)
    chunked = sweep_clusters_sharded(clusters, cluster_chunk=2)
    for a, b in zip(whole, chunked):
        assert np.array_equal(a.consensus, b.consensus)
        assert a.score == b.score
        assert a.n_iters == b.n_iters
        assert a.converged == b.converged
