"""Single-launch fused megakernel (ops.fused_pallas) vs the 3-launch
split oracle, plus the mega/split routing guards.

The bit-identity tests run the megakernel in Pallas interpret mode on
CPU and compare EVERY output of the fused step — candidate tables,
per-read scores, weighted total, and (stats on) n_errors + union edit
indicators — against dense_pallas.fused_tables_pallas on the same
inputs with np.testing.assert_array_equal (no tolerance): the megakernel
chains fill -> dense -> stats through VMEM/ANY scratch instead of HBM
round trips, and the chaining must not change a single bit. Comparisons
cover only the defined regions (rows < tlen(+1), lanes < n_reads):
padding lanes/columns are garbage by contract on both paths.

Routing guards (fast suite): the megakernel declines to the split path
when the env pins it, when the host traceback needs the exported move
band, or when the chained working set cannot fit the VMEM budget.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
jnp = pytest.importorskip("jax.numpy")

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax, dense_pallas, fill_pallas, fused_pallas

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))


def _problem(tlen=24, n_reads=4, bw=5, seed=3):
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(max(4, tlen - 5), tlen + 6))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, bw, SCORES))
    return template, batch_reads(reads, dtype=np.float32)


def _setup(template, batch):
    tlen = len(template)
    geom = align_jax.batch_geometry(batch, tlen)
    K = fill_pallas.uniform_band_height(
        np.asarray(geom.offset), np.asarray(geom.nd)
    )
    Tmax = ((tlen + 63) // 64) * 64
    T1p = Tmax + 64
    tpl = np.zeros(Tmax, np.int8)
    tpl[:tlen] = template
    return tlen, geom, K, T1p, tpl


def _compare(tlen_n, n_reads, bw, seed, want_stats, zero_w=None):
    template, batch = _problem(tlen=tlen_n, n_reads=n_reads, bw=bw,
                               seed=seed)
    tlen, geom, K, T1p, tpl = _setup(template, batch)
    C = 8
    weights = np.ones(batch.n_reads, np.float32)
    if zero_w is not None:
        weights[zero_w] = 0.0
    args = (jnp.asarray(tpl), jnp.int32(tlen), _setup_bufs(batch), geom,
            jnp.asarray(weights), K, T1p, C)
    split = dense_pallas.fused_tables_pallas(
        *args, want_stats=want_stats, interpret=True)
    mega = fused_pallas.fused_tables_auto(
        *args, want_stats=want_stats, interpret=True, impl="mega")
    assert mega["impl"] == "mega"
    N = batch.n_reads
    T1 = tlen + 1
    np.testing.assert_array_equal(
        np.asarray(mega["scores"])[:N], np.asarray(split["scores"])[:N])
    np.testing.assert_array_equal(
        np.asarray(mega["total"]), np.asarray(split["total"]))
    for name, hi in (("sub", tlen), ("ins", tlen + 1), ("del", tlen)):
        np.testing.assert_array_equal(
            np.asarray(mega[name])[:hi], np.asarray(split[name])[:hi],
            err_msg=name)
    if want_stats:
        np.testing.assert_array_equal(
            np.asarray(mega["n_errors"])[:N],
            np.asarray(split["n_errors"])[:N])
        np.testing.assert_array_equal(
            np.asarray(mega["edits"])[:T1], np.asarray(split["edits"])[:T1])


def _setup_bufs(batch):
    Npad = ((batch.n_reads + 127) // 128) * 128
    return fill_pallas.build_fill_buffers(
        jnp.asarray(batch.seq), jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
        jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
    )


# ---- interpret-mode grid: megakernel vs 3-launch oracle (slow; the CI
# kernels job runs these under both RIFRAF_TPU_FUSED_IMPL settings) ----


@pytest.mark.slow
@pytest.mark.parametrize("want_stats", [False, True])
def test_mega_matches_split_oracle(want_stats):
    """Multi-grid-step geometry (tlen=20 spans several C=8 column
    blocks), stats chain off and on."""
    _compare(20, 3, 4, 7, want_stats)


@pytest.mark.slow
def test_mega_matches_split_zero_weight_lane():
    """A weight-0 read must drop out of the weighted tables and total
    identically on both paths (the lane-packing masking contract)."""
    _compare(24, 4, 5, 3, True, zero_w=1)


@pytest.mark.slow
def test_mega_matches_split_wide_band():
    """bw=4 at tlen=16: band height comparable to the column block, so
    phase-2 backward windows clamp at the buffer edge."""
    _compare(16, 3, 4, 11, True)


@pytest.mark.slow
def test_mega_matches_split_long_template():
    """tlen=40 crosses the T1p midpoint: exercises the clamped backward
    window base and the per-lane roll realignment over many steps."""
    _compare(40, 3, 4, 13, True)


# ---- routing guards (fast): decline conditions are host arithmetic ----


def test_mega_declines_when_vmem_budget_too_small():
    """The planner guard: when plan_cols cannot fit the chained working
    set (dual fill + dense join + stats tiles) in the VMEM budget even
    at 1 column, the megakernel declines and routing falls back to the
    split 3-launch path."""
    ok, reason = fused_pallas.mega_eligible(128, 16, want_stats=True,
                                            vmem_budget=4096)
    assert not ok
    assert "VMEM" in reason
    sel, _ = fused_pallas.select_impl(128, 16, want_stats=True,
                                      vmem_budget=4096, impl="mega")
    assert sel == "split"


def test_mega_eligible_at_default_budget():
    ok, reason = fused_pallas.mega_eligible(128, 16, want_stats=True,
                                            impl="mega")
    assert ok and reason == "mega"
    plan = fused_pallas.mega_plan(128, 16, want_stats=True)
    assert plan.fits and plan.cols >= 1


def test_mega_declines_on_want_moves():
    """The SCORE-stage host traceback consumes the exported move band;
    the megakernel keeps moves in launch-private scratch, so it must
    route split."""
    ok, reason = fused_pallas.mega_eligible(128, 16, want_moves=True,
                                            impl="mega")
    assert not ok and "moves" in reason


def test_env_split_pins_oracle(monkeypatch):
    monkeypatch.setenv("RIFRAF_TPU_FUSED_IMPL", "split")
    assert fused_pallas.fused_impl() == "split"
    sel, reason = fused_pallas.select_impl(128, 16)
    assert sel == "split" and "RIFRAF_TPU_FUSED_IMPL" in reason
    monkeypatch.delenv("RIFRAF_TPU_FUSED_IMPL")
    assert fused_pallas.select_impl(128, 16)[0] == "mega"


def test_mega_plan_scales_columns_with_budget():
    """More VMEM -> at least as many columns per grid step; the fused
    plan never exceeds the dense cap."""
    small = fused_pallas.mega_plan(256, 16, vmem_budget=2 << 20)
    big = fused_pallas.mega_plan(256, 16, vmem_budget=32 << 20)
    assert big.cols >= small.cols
    assert big.cols <= 128  # _COL_CAPS["fused"]: min(T1p // 2, 256)
