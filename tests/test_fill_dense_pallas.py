"""Oracle tests for the second-generation Pallas engines.

The kernels themselves run in interpret mode here (the suite forces the
CPU backend) and are SLOW to trace, so the full fill+dense oracle is
marked slow; the pure-XLA helpers (backward alignment, halo blocking)
are tested cheaply against the flip oracle. On-TPU equivalence runs via
exp/fill_pallas_check.py / exp/dense_pallas_check.py and the driver
equality tests.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax, dense_pallas, fill_pallas

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))


def _problem(tlen=24, n_reads=4, bw=5, seed=3):
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(max(4, tlen - 5), tlen + 6))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, bw, SCORES))
    return template, batch_reads(reads, dtype=np.float32)


def _setup(template, batch):
    tlen = len(template)
    geom = align_jax.batch_geometry(batch, tlen)
    K = fill_pallas.uniform_band_height(
        np.asarray(geom.offset), np.asarray(geom.nd)
    )
    Tmax = ((tlen + 63) // 64) * 64
    T1p = Tmax + 64
    tpl = np.zeros(Tmax, np.int8)
    tpl[:tlen] = template
    Npad = ((batch.n_reads + 127) // 128) * 128
    bufs = fill_pallas.build_fill_buffers(
        jnp.asarray(batch.seq), jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
        jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
    )
    return tlen, geom, K, Tmax, T1p, tpl, Npad, bufs


def test_backward_halo_blocks_matches_flip_oracle():
    """backward_halo_blocks (the memory-lean blocked flip+shift) must
    reproduce flip_reversed_uniform's backward band on every in-band
    cell, for every halo block."""
    template, batch = _problem()
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs = _setup(
        template, batch
    )
    # reversed-problem forward band via the XLA oracle path: backward
    # fill of align_jax gives B directly; reconstruct Brev from it by
    # inverting the flip relation on a synthetic random band instead —
    # simpler: make a random Brev and compare both mappings of it.
    rng = np.random.default_rng(0)
    Brev = rng.normal(size=(Npad, K, T1p)).astype(np.float32)
    Brev_flat = jnp.asarray(
        np.ascontiguousarray(Brev.transpose(2, 1, 0).reshape(T1p * K, Npad))
    )
    OFF = jnp.max(geom.offset).astype(jnp.int32)

    # oracle mapping: B[k, d, j] = Brev[k, S_k - d, tlen - j]
    B_oracle = fill_pallas.flip_reversed_uniform(
        jnp.asarray(Brev), jnp.int32(tlen), bufs.lengths, OFF, K
    )
    B_oracle = np.asarray(B_oracle)

    for C in (32, 64):
        if T1p % C:
            continue
        Bh = np.asarray(dense_pallas.backward_halo_blocks(
            Brev_flat, jnp.int32(tlen), OFF, bufs.lengths,
            K, T1p, C,
        ))
        n_steps = T1p // C
        slen = np.asarray(bufs.lengths)
        off = np.asarray(geom.offset)
        for jb in range(n_steps):
            blk = Bh[jb].reshape(C + 1, K, Npad)
            for c in range(C + 1):
                j = jb * C + c
                if j > tlen:
                    continue  # garbage by contract
                for k in range(batch.n_reads):
                    # compare in-band rows only (rolled-in rows are
                    # garbage by contract)
                    S = slen[k] - tlen + 2 * int(OFF)
                    d_ok = np.arange(K)
                    d_ok = d_ok[(S - d_ok >= 0) & (S - d_ok < K)]
                    np.testing.assert_array_equal(
                        blk[c, d_ok, k], B_oracle[k, d_ok, j],
                        err_msg=f"C={C} jb={jb} c={c} read={k}",
                    )


@pytest.mark.slow
def test_fused_step_pallas_matches_xla_dense_interpret():
    """Full fill+backward+dense Pallas pipeline (interpret mode) ==
    the XLA dense sweep oracle."""
    from rifraf_tpu.ops.proposal_dense import score_all_edits

    template, batch = _problem(tlen=20, n_reads=3, bw=4, seed=7)
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs = _setup(
        template, batch
    )
    # small C: interpret-mode tracing cost scales with the per-step
    # column unroll; correctness is C-independent
    C = 8
    weights = np.ones(batch.n_reads, np.float32)
    weights[1] = 0.0  # zero-weight masking
    packed, _ = dense_pallas.fused_step_pallas(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom,
        jnp.asarray(weights), K, T1p, C, interpret=True,
    )
    packed = np.asarray(packed)
    lay = dense_pallas.pack_layout_pallas(Npad, T1p)
    sub_t = packed[slice(*lay["sub"])].reshape(T1p, 4)
    ins_t = packed[slice(*lay["ins"])].reshape(T1p, 4)
    del_t = packed[slice(*lay["del"])]
    sc = packed[slice(*lay["scores"])][: batch.n_reads]

    Kx = align_jax.band_height(batch, tlen)
    A, _, scores_x, _ = align_jax.forward_batch(tpl, batch, tlen=tlen, K=Kx)
    B, _, _ = align_jax.backward_batch(tpl, batch, tlen=tlen, K=Kx)
    sub_x, ins_x, del_x = (np.asarray(v) for v in score_all_edits(
        A, B, batch, geom, jnp.asarray(weights)
    ))
    np.testing.assert_allclose(sc, np.asarray(scores_x), rtol=1e-5, atol=1e-5)
    for got, want, hi in ((sub_t, sub_x, tlen), (ins_t, ins_x, tlen + 1),
                          (del_t, del_x, tlen)):
        g, w = got[:hi], want[:hi]
        finite = np.isfinite(w)
        np.testing.assert_allclose(g[finite], w[finite], rtol=2e-5, atol=2e-5)
        assert (g[~finite] < -1e30).all()


@pytest.mark.slow
def test_panel_fused_matches_single_launch_interpret():
    """The panel-blocked long-template path (carry-chained fill panels +
    per-panel dense slices) must reproduce the single-launch fused step:
    identical scores, tables, stats."""
    template, batch = _problem(tlen=40, n_reads=3, bw=4, seed=13)
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs = _setup(
        template, batch
    )
    C = 8
    weights = np.ones(batch.n_reads, np.float32)
    one = dense_pallas.fused_tables_pallas(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom,
        jnp.asarray(weights), K, T1p, C, want_stats=True,
        interpret=True,
    )
    pan = dense_pallas.fused_tables_pallas_panels(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom,
        jnp.asarray(weights), K, T1p, C,
        panel_cols=16, want_stats=True, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(pan["total"]), np.asarray(one["total"]),
        rtol=1e-6, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(pan["scores"]), np.asarray(one["scores"]),
        rtol=1e-6, atol=1e-6,
    )
    for name in ("sub", "ins", "del"):
        a, b = np.asarray(pan[name]), np.asarray(one[name])
        hi = tlen + 1
        m = b[:hi] > -1e30
        np.testing.assert_allclose(
            a[:hi][m], b[:hi][m], rtol=1e-5, atol=1e-5, err_msg=name
        )
    np.testing.assert_array_equal(
        np.asarray(pan["n_errors"]), np.asarray(one["n_errors"])
    )
    np.testing.assert_array_equal(
        np.asarray(pan["edits"]), np.asarray(one["edits"])
    )


@pytest.mark.slow
def test_pallas_moves_and_stats_match_xla_interpret():
    """In-kernel move recording (interpret mode): the uniform-frame move
    band must equal the XLA scan's per-read-frame moves row-for-row
    (shifted by each read's frame delta), and the traceback statistics
    built from it (n_errors + union edit indicators) must match the XLA
    want_stats components exactly."""
    template, batch = _problem(tlen=16, n_reads=3, bw=4, seed=11)
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs = _setup(
        template, batch
    )
    C = 8
    A_u, _, sc_u, OFF, moves_u = fill_pallas.fill_uniform(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom, K, T1p, C,
        with_backward=False, want_moves=True, interpret=True,
    )
    moves_u = np.asarray(moves_u)

    Kx = align_jax.band_height(batch, tlen)
    _, moves_x, scores_x, _ = align_jax.forward_batch(
        tpl, batch, tlen=tlen, K=Kx, want_moves=True
    )
    moves_x = np.asarray(moves_x)
    np.testing.assert_allclose(
        np.asarray(sc_u)[: batch.n_reads], np.asarray(scores_x),
        rtol=1e-5, atol=1e-5,
    )
    off = np.asarray(geom.offset)
    delta = int(OFF) - off
    T1 = tlen + 1
    Ax = np.asarray(
        align_jax.forward_batch(tpl, batch, tlen=tlen, K=Kx)[0]
    )
    slen = np.asarray(geom.slen)
    for k in range(batch.n_reads):
        dk = int(delta[k])
        # uniform row d holds per-read row d - delta_k; rows past the
        # uniform buffer exist only when another read's frame is taller,
        # and are all TRACE_NONE in the per-read band
        hi = min(dk + Kx, moves_u.shape[1])
        got = moves_u[k, dk:hi, :T1]
        want = moves_x[k, : hi - dk, :T1]
        assert (moves_x[k, hi - dk :, :T1] == 0).all()
        # the two engines order the insert-chain G-sums differently, so
        # candidates that tie exactly in one engine differ by an ulp in
        # the other — move equality is only required at cells whose
        # top-two candidates are separated; ambiguous cells must still
        # record a move consistent with the cell value
        sq, mt = np.asarray(batch.seq)[k], np.asarray(batch.match)[k]
        mm, gi = np.asarray(batch.mismatch)[k], np.asarray(batch.ins)[k]
        dl = np.asarray(batch.dels)[k]
        n_ambiguous = 0
        for d in range(hi - dk):
            for j in range(T1):
                if got[d, j] == want[d, j]:
                    continue
                i = d + j - int(off[k])
                cands = [-np.inf, -np.inf, -np.inf]
                if j > 0 and 1 <= i <= slen[k]:
                    msc = mt[i - 1] if sq[i - 1] == tpl[j - 1] else mm[i - 1]
                    cands[0] = Ax[k, d, j - 1] + msc
                if j > 0 and d + 1 < Kx and i <= slen[k]:
                    cands[1] = Ax[k, d + 1, j - 1] + dl[i]
                if d > 0 and 1 <= i <= slen[k]:
                    cands[2] = Ax[k, d - 1, j] + gi[i - 1]
                top2 = sorted(cands)[-2:]
                assert top2[1] - top2[0] < 1e-4, (
                    f"read {k} d={d} j={j}: moves differ at an "
                    f"unambiguous cell ({got[d, j]} vs {want[d, j]}, "
                    f"cands {cands})"
                )
                n_ambiguous += 1
        assert n_ambiguous <= 8, "too many tie cells to trust the oracle"

    # stats from the Pallas move band == the XLA want_stats components
    nerr_u, edits_u = dense_pallas.stats_from_moves(
        jnp.asarray(moves_u[:, :, :Tmax + 1]), bufs.seq_T.T,
        jnp.asarray(tpl), geom, bufs.lengths, K,
    )
    stats = jax.vmap(
        align_jax._traceback_stats_one, in_axes=(0, 0, None, 0, None)
    )
    nerr_x, edits_x = stats(
        jnp.asarray(moves_x), jnp.asarray(batch.seq), jnp.asarray(tpl),
        geom, Kx,
    )
    np.testing.assert_array_equal(
        np.asarray(nerr_u)[: batch.n_reads], np.asarray(nerr_x)
    )
    np.testing.assert_array_equal(
        np.asarray(edits_u), np.asarray(jnp.max(edits_x, axis=0))
    )


@pytest.mark.slow
def test_fill_stats_pallas_packed_interpret():
    """fill_stats_pallas (the adaptation-round program) returns the same
    scores and error counts as the full-fat paths."""
    template, batch = _problem(tlen=16, n_reads=2, bw=4, seed=5)
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs = _setup(
        template, batch
    )
    packed = np.asarray(dense_pallas.fill_stats_pallas(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom, K, T1p, 8,
        interpret=True,
    ))
    scores_p = packed[:Npad][: batch.n_reads]
    nerr_p = packed[Npad : 2 * Npad][: batch.n_reads].astype(np.int64)

    Kx = align_jax.band_height(batch, tlen)
    _, moves_x, scores_x, _ = align_jax.forward_batch(
        tpl, batch, tlen=tlen, K=Kx, want_moves=True
    )
    stats = jax.vmap(
        align_jax._traceback_stats_one, in_axes=(0, 0, None, 0, None)
    )
    nerr_x, _ = stats(
        moves_x, jnp.asarray(batch.seq), jnp.asarray(tpl), geom, Kx
    )
    np.testing.assert_allclose(scores_p, np.asarray(scores_x),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(nerr_p, np.asarray(nerr_x))
