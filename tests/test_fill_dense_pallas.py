"""Oracle tests for the second-generation Pallas engines.

The kernels themselves run in interpret mode here (the suite forces the
CPU backend) and are SLOW to trace, so the full fill+dense oracle is
marked slow; the pure-XLA helpers (backward alignment, halo blocking)
are tested cheaply against the flip oracle. On-TPU equivalence runs via
exp/fill_pallas_check.py / exp/dense_pallas_check.py and the driver
equality tests.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from rifraf_tpu.models.errormodel import ErrorModel, Scores
from rifraf_tpu.models.sequences import batch_reads, make_read_scores
from rifraf_tpu.ops import align_jax, dense_pallas, fill_pallas

SCORES = Scores.from_error_model(ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0))


def _problem(tlen=24, n_reads=4, bw=5, seed=3):
    rng = np.random.default_rng(seed)
    template = rng.integers(0, 4, size=tlen).astype(np.int8)
    reads = []
    for _ in range(n_reads):
        slen = int(rng.integers(max(4, tlen - 5), tlen + 6))
        s = rng.integers(0, 4, size=slen).astype(np.int8)
        log_p = rng.uniform(-3.0, -1.0, size=slen)
        reads.append(make_read_scores(s, log_p, bw, SCORES))
    return template, batch_reads(reads, dtype=np.float32)


def _setup(template, batch):
    tlen = len(template)
    geom = align_jax.batch_geometry(batch, tlen)
    K = fill_pallas.uniform_band_height(
        np.asarray(geom.offset), np.asarray(geom.nd)
    )
    Tmax = ((tlen + 63) // 64) * 64
    T1p = Tmax + 64
    tpl = np.zeros(Tmax, np.int8)
    tpl[:tlen] = template
    Npad = ((batch.n_reads + 127) // 128) * 128
    bufs = fill_pallas.build_fill_buffers(
        jnp.asarray(batch.seq), jnp.asarray(batch.match),
        jnp.asarray(batch.mismatch), jnp.asarray(batch.ins),
        jnp.asarray(batch.dels), jnp.asarray(batch.lengths), Npad,
    )
    lengths = np.asarray(batch.lengths)
    r_unique = tuple(sorted({int(v) for v in lengths - lengths.min()}))
    return tlen, geom, K, Tmax, T1p, tpl, Npad, bufs, r_unique


def test_backward_halo_blocks_matches_flip_oracle():
    """backward_halo_blocks (the memory-lean blocked flip+shift) must
    reproduce flip_reversed_uniform's backward band on every in-band
    cell, for every halo block."""
    template, batch = _problem()
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs, r_unique = _setup(
        template, batch
    )
    # reversed-problem forward band via the XLA oracle path: backward
    # fill of align_jax gives B directly; reconstruct Brev from it by
    # inverting the flip relation on a synthetic random band instead —
    # simpler: make a random Brev and compare both mappings of it.
    rng = np.random.default_rng(0)
    Brev = rng.normal(size=(Npad, K, T1p)).astype(np.float32)
    Brev_flat = jnp.asarray(
        np.ascontiguousarray(Brev.transpose(2, 1, 0).reshape(T1p * K, Npad))
    )
    OFF = jnp.max(geom.offset).astype(jnp.int32)

    # oracle mapping: B[k, d, j] = Brev[k, S_k - d, tlen - j]
    B_oracle = fill_pallas.flip_reversed_uniform(
        jnp.asarray(Brev), jnp.int32(tlen), bufs.lengths, OFF, K
    )
    B_oracle = np.asarray(B_oracle)

    for C in (32, 64):
        if T1p % C:
            continue
        Bh = np.asarray(dense_pallas.backward_halo_blocks(
            Brev_flat, jnp.int32(tlen), OFF, bufs.lengths, r_unique,
            K, T1p, C,
        ))
        n_steps = T1p // C
        slen = np.asarray(bufs.lengths)
        off = np.asarray(geom.offset)
        for jb in range(n_steps):
            blk = Bh[jb].reshape(C + 1, K, Npad)
            for c in range(C + 1):
                j = jb * C + c
                if j > tlen:
                    continue  # garbage by contract
                for k in range(batch.n_reads):
                    # compare in-band rows only (rolled-in rows are
                    # garbage by contract)
                    S = slen[k] - tlen + 2 * int(OFF)
                    d_ok = np.arange(K)
                    d_ok = d_ok[(S - d_ok >= 0) & (S - d_ok < K)]
                    np.testing.assert_array_equal(
                        blk[c, d_ok, k], B_oracle[k, d_ok, j],
                        err_msg=f"C={C} jb={jb} c={c} read={k}",
                    )


@pytest.mark.slow
def test_fused_step_pallas_matches_xla_dense_interpret():
    """Full fill+backward+dense Pallas pipeline (interpret mode) ==
    the XLA dense sweep oracle."""
    from rifraf_tpu.ops.proposal_dense import score_all_edits

    template, batch = _problem(tlen=20, n_reads=3, bw=4, seed=7)
    tlen, geom, K, Tmax, T1p, tpl, Npad, bufs, r_unique = _setup(
        template, batch
    )
    # small C: interpret-mode tracing cost scales with the per-step
    # column unroll; correctness is C-independent
    C = 8
    weights = np.ones(batch.n_reads, np.float32)
    weights[1] = 0.0  # zero-weight masking
    packed = np.asarray(dense_pallas.fused_step_pallas(
        jnp.asarray(tpl), jnp.int32(tlen), bufs, geom,
        jnp.asarray(weights), K, T1p, C, r_unique, interpret=True,
    ))
    lay = dense_pallas.pack_layout_pallas(Npad, T1p)
    sub_t = packed[slice(*lay["sub"])].reshape(T1p, 4)
    ins_t = packed[slice(*lay["ins"])].reshape(T1p, 4)
    del_t = packed[slice(*lay["del"])]
    sc = packed[slice(*lay["scores"])][: batch.n_reads]

    Kx = align_jax.band_height(batch, tlen)
    A, _, scores_x, _ = align_jax.forward_batch(tpl, batch, tlen=tlen, K=Kx)
    B, _, _ = align_jax.backward_batch(tpl, batch, tlen=tlen, K=Kx)
    sub_x, ins_x, del_x = (np.asarray(v) for v in score_all_edits(
        A, B, batch, geom, jnp.asarray(weights)
    ))
    np.testing.assert_allclose(sc, np.asarray(scores_x), rtol=1e-5, atol=1e-5)
    for got, want, hi in ((sub_t, sub_x, tlen), (ins_t, ins_x, tlen + 1),
                          (del_t, del_x, tlen)):
        g, w = got[:hi], want[:hi]
        finite = np.isfinite(w)
        np.testing.assert_allclose(g[finite], w[finite], rtol=2e-5, atol=2e-5)
        assert (g[~finite] < -1e30).all()
