"""Online consensus service (rifraf_tpu.serve): admission, flush
policy, typed rejections, fallback equality, and (slow) end-to-end
bit-identity of served results vs the per-cluster driver."""

import json
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from rifraf_tpu import serve
from rifraf_tpu.engine.driver import rifraf
from rifraf_tpu.engine.params import RifrafParams
from rifraf_tpu.models.errormodel import ErrorModel
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.parallel.cluster import PipelineJobError, pipeline_map
from rifraf_tpu.serve.batcher import MicroBatcher
from rifraf_tpu.serve.request import Request
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.phred import phred_to_log_p
from rifraf_tpu.utils.timers import Timers

SEQ_ERRORS = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)


def _cluster(nseqs=3, length=30, seed=0):
    rng = np.random.default_rng(seed)
    params = RifrafParams()
    _, _, _, seqs, _, phreds, _, _ = sample_sequences(
        nseqs=nseqs, length=length, error_rate=0.02, rng=rng,
        seq_errors=SEQ_ERRORS,
    )
    return [
        make_read_scores(s, phred_to_log_p(np.asarray(p, float)),
                         params.bandwidth, params.scores)
        for s, p in zip(seqs, phreds)
    ]


def _ref(cluster, do_alignment_proposals=False, device_loop=None):
    kw = {} if device_loop is None else {"device_loop": device_loop}
    return rifraf(
        [r.seq for r in cluster],
        error_log_ps=[r.error_log_p for r in cluster],
        params=RifrafParams(batch_size=0, batch_fixed=False,
                            do_alignment_proposals=do_alignment_proposals,
                            **kw),
    )


# ---------------------------------------------------------------- satellites


def test_timers_to_dict():
    t = Timers()
    with t.time("outer"):
        with t.time("inner"):
            pass
    with t.time("inner"):
        pass
    d = t.to_dict()
    assert set(d) == {"outer", "inner"}
    assert d["inner"]["calls"] == 2
    assert d["outer"]["seconds"] >= 0.0
    json.dumps(d)  # JSON-serializable as exported


def test_driver_declines_metadata():
    """Config-level device-loop declines surface as structured
    metadata entries, not just verbose log lines."""
    c = _cluster(seed=3)
    res = rifraf(
        [r.seq for r in c], error_log_ps=[r.error_log_p for r in c],
        params=RifrafParams(batch_size=0, batch_fixed=False,
                            min_dist=1, device_loop="on"),
    )
    declines = res.metadata["declines"]
    assert declines, "min_dist=1 must decline the device loop"
    assert all(set(d) == {"stage", "reason"} for d in declines)
    assert any("min_dist" in d["reason"] for d in declines)


def test_pipeline_map_on_error_return_isolates_jobs():
    def pack(x):
        return x

    def run(x):
        if x == 1:
            raise ValueError("boom at run")
        return x * 10

    def collect(x):
        if x == 20:
            raise KeyError("boom at collect")
        return x + 1

    out = pipeline_map(pack, run, collect, [0, 1, 2, 3],
                       on_error="return")
    assert out[0] == 1 and out[3] == 31
    assert isinstance(out[1], PipelineJobError)
    assert out[1].job_index == 1 and out[1].stage == "run"
    assert isinstance(out[1].__cause__, ValueError)
    assert isinstance(out[2], PipelineJobError)
    assert out[2].job_index == 2 and out[2].stage == "collect"


# ------------------------------------------------------------- micro-batcher


def _fake_request(rid, key, t_submit, deadline=None):
    return Request(id=rid, cluster=[], info=None, key=key,
                   t_submit=t_submit, deadline=deadline)


def test_microbatcher_flush_policy_fake_clock():
    # segment packing would group ka/kb by their shared shape axes;
    # this test pins the classic per-key policy (the timing logic is
    # identical either way)
    cfg = serve.ServeConfig(max_batch=3, max_wait_ms=20.0,
                            deadline_margin_ms=50.0, segment_pack=False)
    b = MicroBatcher(cfg)
    ka, kb = (8, 64, 64, 16), (16, 64, 64, 16)

    # occupancy flush: the 3rd same-key request returns the bucket
    assert b.add(_fake_request("a0", ka, 0.0)) is None
    assert b.add(_fake_request("b0", kb, 0.0)) is None
    assert b.add(_fake_request("a1", ka, 0.001)) is None
    full = b.add(_fake_request("a2", ka, 0.002))
    assert [r.id for r in full] == ["a0", "a1", "a2"]
    assert b.depth() == 1  # kb still pending

    # max-wait flush: due() pops kb once its oldest waited 20 ms
    assert b.due(now=0.010) == []
    assert b.next_due(now=0.010) == pytest.approx(0.010)
    (timed,) = b.due(now=0.021)
    assert [r.id for r in timed] == ["b0"]
    assert b.depth() == 0

    # deadline-risk flush: a fresh request whose deadline is inside the
    # margin flushes immediately even though max_wait hasn't elapsed
    b.add(_fake_request("c0", ka, 1.0, deadline=1.040))
    (risk,) = b.due(now=1.0)
    assert [r.id for r in risk] == ["c0"]

    # drain returns everything left
    b.add(_fake_request("d0", ka, 2.0))
    b.add(_fake_request("d1", kb, 2.0))
    assert sorted(r.id for f in b.drain() for r in f) == ["d0", "d1"]
    assert b.depth() == 0 and b.next_due(2.0) is None


# ------------------------------------------------- admission / typed errors


def test_queue_full_rejects_instead_of_blocking():
    cfg = serve.ServeConfig(max_queue=2)
    srv = serve.ConsensusServer(cfg, start=False)  # nothing consumes
    c = _cluster()
    t0 = time.perf_counter()
    srv.submit(c)
    srv.submit(c)
    with pytest.raises(serve.QueueFullError) as ei:
        srv.submit(c)
    assert time.perf_counter() - t0 < 5.0  # rejected, not blocked
    assert ei.value.code == "queue_full"
    assert srv.snapshot()["counters"]["rejected_queue_full"] == 1


def test_expired_deadline_yields_typed_error():
    srv = serve.ConsensusServer(serve.ServeConfig(), start=False)
    fut = srv.submit(_cluster(), deadline_ms=1.0)
    time.sleep(0.02)
    srv.start()  # batcher now sees an already-expired request
    resp = fut.result(timeout=30)
    srv.close()
    assert not resp.ok
    assert resp.path == "rejected"
    assert isinstance(resp.error, serve.DeadlineExceededError)
    assert resp.to_json_dict()["error"] == "deadline_exceeded"


def test_hard_rejects_are_synchronous_and_typed():
    cfg = serve.ServeConfig(max_reads=4, max_len=64)
    srv = serve.ConsensusServer(cfg, start=False)
    with pytest.raises(serve.EmptyClusterError):
        srv.submit([])
    with pytest.raises(serve.OversizeError):
        srv.submit(_cluster(nseqs=6))  # > max_reads
    with pytest.raises(serve.OversizeError):
        srv.submit(_cluster(length=100))  # > max_len
    srv._closed = True
    with pytest.raises(serve.ServerClosedError):
        srv.submit(_cluster())


def test_response_wire_form():
    ok = serve.Response(id="x", ok=True,
                        consensus=np.array([0, 1, 2, 3], np.int8),
                        score=-1.5, n_iters=2, converged=True,
                        latency_s=0.0123)
    d = ok.to_json_dict()
    assert d == {"id": "x", "ok": True, "consensus": "ACGT",
                 "score": -1.5, "n_iters": 2, "converged": True,
                 "latency_ms": 12.3, "path": "batched"}
    bad = serve.Response(id="y", ok=False,
                         error=serve.OversizeError("too big"),
                         path="rejected")
    d = bad.to_json_dict()
    assert d["ok"] is False and d["error"] == "oversize"
    json.dumps(d)


def test_encode_cluster_requires_quality():
    with pytest.raises(ValueError):
        serve.encode_cluster(["ACGT"])


# ------------------------------------------------------------ fallback path


def test_oversize_for_batch_falls_back_to_device_loop():
    """Requests over the batched grid limits run as per-cluster
    fallbacks and must equal the direct rifraf() run in the same
    configuration."""
    cfg = serve.ServeConfig(batch_max_reads=1, max_iters=100)
    clusters = [_cluster(seed=s) for s in (1, 2)]
    with serve.ConsensusServer(cfg) as srv:
        resps = [srv.submit(c).result(timeout=120) for c in clusters]
        snap = srv.snapshot()
    assert snap["counters"]["fallback"] == 2
    assert snap["latency_ms"]["n"] == 2
    for c, r in zip(clusters, resps):
        assert r.ok and r.path == "fallback"
        ref = _ref(c)
        assert np.array_equal(r.consensus, ref.consensus)
        assert np.isclose(r.score, float(ref.state.score), rtol=1e-6)
        assert r.n_iters == int(ref.state.stage_iterations.sum())


def test_submit_many_keeps_input_alignment_through_rejects():
    cfg = serve.ServeConfig(batch_max_reads=1)  # all-fallback: no compiles
    clusters = [_cluster(seed=1), [], _cluster(seed=2)]
    resps = serve.submit_many(clusters, config=cfg)
    assert len(resps) == 3
    assert resps[0].ok and resps[2].ok
    assert not resps[1].ok
    assert isinstance(resps[1].error, serve.EmptyClusterError)
    assert [r.id for r in resps] == ["c0", "c1", "c2"]


# ------------------------------------------------------- end-to-end (slow)


@pytest.mark.slow
@pytest.mark.parametrize("dap", [False, True])
def test_served_results_bit_identical_to_driver(dap):
    """A shuffled heterogeneous workload served through warmed
    micro-batches must be bit-identical, per request, to the
    per-cluster device-loop driver — for both candidate algorithms."""
    rng = np.random.default_rng(7)
    pool = []
    for nseqs, length, seed in [(4, 50, 1), (6, 90, 2), (5, 50, 3),
                                (6, 92, 4), (4, 52, 5), (3, 30, 6)]:
        pool.append(_cluster(nseqs=nseqs, length=length, seed=seed))
    shuffled = [pool[i] for i in rng.permutation(len(pool))]
    cfg = serve.ServeConfig(max_batch=4, max_wait_ms=5.0,
                            do_alignment_proposals=dap)
    with serve.ConsensusServer(cfg) as srv:
        assert srv.warmup(shuffled, batch_sizes=(1, 4)) > 0
        resps = serve.submit_many(shuffled, server=srv)
        snap = srv.snapshot()
    assert snap["batches"] >= 1 and snap["batch_occupancy"] > 0
    for c, r in zip(shuffled, resps):
        assert r.ok, r.error
        ref = _ref(c, do_alignment_proposals=dap, device_loop="on")
        assert np.array_equal(r.consensus, ref.consensus)
        assert np.isclose(r.score, float(ref.state.score), rtol=1e-6)
        assert r.n_iters == int(ref.state.stage_iterations.sum())
        assert r.converged == bool(ref.state.converged)


@pytest.mark.slow
def test_cli_serve_watch_once(tmp_path):
    from rifraf_tpu.cli.serve import main as serve_main

    seqs = ["ACGTACGTACGTACGTACGTACGT"] * 3
    reqs = [
        {"id": f"q{i}", "seqs": seqs,
         "phreds": [[20] * len(s) for s in seqs]}
        for i in range(2)
    ]
    reqs.append({"id": "bad", "seqs": ["ACGT"]})  # no quality info
    (tmp_path / "in.jsonl").write_text(
        "\n".join(json.dumps(r) for r in reqs) + "\n")
    rc = serve_main(["--watch", str(tmp_path), "--watch-once",
                     "--max-iters", "8", "--max-batch", "2"])
    assert rc == 0
    lines = [json.loads(l) for l in
             (tmp_path / "in.out.jsonl").read_text().splitlines()]
    by_id = {d["id"]: d for d in lines}
    assert by_id["q0"]["ok"] and by_id["q1"]["ok"]
    assert by_id["q0"]["consensus"] == by_id["q1"]["consensus"]
    assert not by_id["bad"]["ok"]
    assert by_id["bad"]["error"] == "bad_request"
