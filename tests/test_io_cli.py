"""I/O round-trips, simulator statistics, and end-to-end CLI runs.

The CLI-on-shipped-data check mirrors the reference's de-facto golden test
(docs/src/examples.md:60-69 + data/): running the batch consensus CLI on
data/input-reads-*.fastq with data/references.fasta must reproduce each
cluster's true template.
"""

import os

import numpy as np
import pytest

from rifraf_tpu.cli.consensus import main as consensus_main
from rifraf_tpu.cli.shifts import main as shifts_main
from rifraf_tpu.io.fastx import (
    read_fasta,
    read_fastq,
    read_samples,
    write_fasta,
    write_fastq,
    write_samples,
)
from rifraf_tpu.sim.sample import sample_mixture, sample_sequences
from rifraf_tpu.utils.constants import decode_seq, encode_seq

DATA = os.path.join(os.path.dirname(__file__), "..", "data")


def test_fasta_roundtrip(tmp_path):
    path = str(tmp_path / "test.fasta")
    seqs = [encode_seq("ACGTACGT"), encode_seq("TTTT")]
    write_fasta(path, seqs, names=["a", "b"])
    got = read_fasta(path)
    assert [decode_seq(s) for s in got] == ["ACGTACGT", "TTTT"]


def test_fastq_roundtrip(tmp_path):
    path = str(tmp_path / "test.fastq")
    seqs = [encode_seq("ACGT"), encode_seq("GGCC")]
    phreds = [np.array([10, 20, 30, 40], dtype=np.int8),
              np.array([1, 2, 3, 93], dtype=np.int8)]
    write_fastq(path, seqs, phreds, names=["x", "y"])
    gseqs, gphreds, gnames = read_fastq(path)
    assert [decode_seq(s) for s in gseqs] == ["ACGT", "GGCC"]
    np.testing.assert_array_equal(gphreds[0], phreds[0])
    np.testing.assert_array_equal(gphreds[1], phreds[1])
    assert gnames == ["x", "y"]


def test_fastq_rejects_negative_phreds(tmp_path):
    path = str(tmp_path / "bad.fastq")
    with open(path, "w") as fh:
        fh.write("@s\nAC\n+\n" + chr(33 - 1) + chr(40) + "\n")
    with pytest.raises(ValueError):
        read_fastq(path)


def test_default_names(tmp_path):
    path = str(tmp_path / "t.fastq")
    write_fastq(path, [encode_seq("AC")], [np.array([5, 5], dtype=np.int8)])
    _, _, names = read_fastq(path)
    assert names == ["seq_1"]


def test_samples_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    ref, template, t_p, seqs, actual, phreds, cb, db = sample_sequences(
        nseqs=4, length=40, rng=rng
    )
    prefix = str(tmp_path / "sim")
    write_samples(prefix, ref, template, t_p, seqs, phreds)
    gref, gtemplate, gt_err, gseqs, gphreds = read_samples(prefix)
    assert decode_seq(gref) == decode_seq(ref)
    assert decode_seq(gtemplate) == decode_seq(template)
    assert len(gseqs) == 4


def test_simulator_error_rate_statistics():
    """Mean template error rate tracks the request (test_sample.jl:39-45)."""
    rng = np.random.default_rng(123)
    _, _, t_p, _, _, _, _, _ = sample_sequences(
        nseqs=2, length=5000, error_rate=0.01, alpha=1.0, rng=rng
    )
    assert 0.003 < np.mean(t_p) < 0.03


def test_simulator_mixture_sizes():
    """test_sample.jl:47-55."""
    rng = np.random.default_rng(5)
    ref, templates, t_p, seqs, actual, phreds, cb, db = sample_mixture(
        (3, 2), 50, 3, rng=rng
    )
    assert len(templates) == 2
    assert len(seqs) == 5
    assert len(ref) % 3 == 0


@pytest.mark.slow
def test_consensus_cli_recovers_templates(tmp_path):
    """End-to-end golden run on the shipped example data."""
    out = str(tmp_path / "consensus.fasta")
    rc = consensus_main(
        [
            "--reference", os.path.join(DATA, "references.fasta"),
            "--reference-map", os.path.join(DATA, "ref-map.tsv"),
            "--phred-cap", "30",
            "1,2,2",
            os.path.join(DATA, "input-reads-*.fastq"),
            out,
        ]
    )
    assert rc == 0
    got = read_fasta(out)
    assert len(got) == 2
    for k, seq in enumerate(got, start=1):
        with open(os.path.join(DATA, f"template-{k}.txt")) as fh:
            want = fh.read().strip()
        assert decode_seq(seq) == want, f"cluster {k} consensus != template"


@pytest.mark.slow
def test_consensus_cli_sharded_sweep(tmp_path):
    """--sharded-sweep (one device program for all clusters) recovers
    each cluster's template and rejects reference runs."""
    from rifraf_tpu.models.errormodel import ErrorModel

    rng = np.random.default_rng(11)
    templates = []
    for k in range(2):
        _, template, _, seqs, _, phreds, _, _ = sample_sequences(
            nseqs=4, length=40, error_rate=0.02, rng=rng,
            seq_errors=ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0),
        )
        write_fastq(str(tmp_path / f"cluster-{k}.fastq"), seqs, phreds)
        templates.append(template)
    out = str(tmp_path / "out.fasta")
    rc = consensus_main([
        "1,2,2", str(tmp_path / "cluster-*.fastq"), out, "--sharded-sweep",
    ])
    assert rc == 0
    got = read_fasta(out)
    assert len(got) == 2
    for seq, template in zip(got, templates):
        np.testing.assert_array_equal(seq, template)

    with pytest.raises(ValueError, match="sharded-sweep"):
        consensus_main([
            "--reference", os.path.join(DATA, "references.fasta"),
            "1,2,2", str(tmp_path / "cluster-*.fastq"), out,
            "--sharded-sweep",
        ])


def test_shifts_cli(tmp_path):
    infile = str(tmp_path / "in.fasta")
    outfile = str(tmp_path / "out.fasta")
    # reference first, then sequences sharing it; "broken" drops one C
    # of the CCC codon, so frame correction must re-insert exactly it
    write_fasta(
        infile,
        [encode_seq("AAACCCGGGTTT"), encode_seq("AAACCGGGTTT")],
        names=["ref", "broken"],
    )
    rc = shifts_main([infile, outfile])
    assert rc == 0
    got = read_fasta(outfile)
    assert len(got) == 1
    assert decode_seq(got[0]) == "AAACCCGGGTTT"
