"""Clonal-read consensus workflow: filter, orient, trim, consensus, QVs.

Python equivalent of the reference's real-data notebook pipeline
(notebooks/clonal_code.jl + notebooks/RIFRAF_clonal_accuracy.ipynb): raw
amplicon reads arrive in mixed orientation with primers attached and wide
quality spread. The pipeline is

1. filter reads by mean reported error rate and length near the median
   (clonal_code.jl:11-16 valid_read_indices);
2. orient each read by edit distance: keep the strand closer to the
   reference, reverse-complementing sequence AND phreds otherwise
   (clonal_code.jl:76-83);
3. trim primers by aligning to the reference with terminal insertions
   free (``trim=True``) and cutting the leading/trailing insert runs
   (clonal_code.jl:48-63 trim_ends_indices);
4. run the consensus with the reference and per-base quality estimation
   (do_score), like the notebook's accuracy run (3.6 s anchor,
   RIFRAF_clonal_accuracy.ipynb cell 6).

Real HIV reads are not shipped; the same pipeline runs here on simulated
reads that are given the notebook data's pathologies (random orientation,
primers, quality spread).

Run:  python examples/clonal_workflow.py        (TPU if visible)
"""

import os
import sys
import time

import numpy as np

# runnable without installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rifraf_tpu import (
    ErrorModel,
    RifrafParams,
    Scores,
    decode_seq,
    estimate_point_probs,
    reverse_complement,
    rifraf,
)
from rifraf_tpu.models.sequences import make_read_scores
from rifraf_tpu.ops import align_np
from rifraf_tpu.sim.sample import sample_from_template, sample_sequences
from rifraf_tpu.utils.phred import phred_to_p


def make_messy_reads(rng, template, reference, n_reads=24):
    """Simulated reads OF THE GIVEN TEMPLATE with the notebook data's
    pathologies (sample_sequences would draw its own fresh template)."""
    template_error_p = np.full(len(template), 0.005)
    seq_errors = ErrorModel(1.0, 2.0, 2.0, 0.0, 0.0)
    seqs, phreds = [], []
    for _ in range(n_reads):
        s, _, p, _, _ = sample_from_template(
            rng, template, template_error_p, seq_errors,
            phred_scale=1.5, actual_std=3.0, reported_std=1.0,
        )
        seqs.append(s)
        phreds.append(p)
    fwd_primer = rng.integers(0, 4, size=20).astype(np.int8)
    rev_primer = rng.integers(0, 4, size=20).astype(np.int8)
    out_seqs, out_phreds = [], []
    for s, p in zip(seqs, phreds):
        s = np.concatenate([fwd_primer, s, rev_primer])
        p = np.concatenate(
            [np.full(20, 20, dtype=p.dtype), p, np.full(20, 20, dtype=p.dtype)]
        )
        if rng.random() < 0.5:  # random strand orientation
            s = reverse_complement(s)
            p = p[::-1].copy()
        out_seqs.append(s)
        out_phreds.append(p)
    # a few junk reads the filter should drop
    for _ in range(3):
        n = int(rng.integers(30, 60))
        out_seqs.append(rng.integers(0, 4, size=n).astype(np.int8))
        out_phreds.append(np.full(n, 3, dtype=np.int8))
    return out_seqs, out_phreds


def filter_reads(seqs, phreds, error_range=(0.0, 0.1), length_cutoff=40):
    """clonal_code.jl:11-16: mean reported error + length near median."""
    mean_errors = [float(np.mean(phred_to_p(p))) for p in phreds]
    median_len = np.median([len(s) for s in seqs])
    keep = [
        i for i in range(len(seqs))
        if error_range[0] <= mean_errors[i] <= error_range[1]
        and abs(len(seqs[i]) - median_len) < length_cutoff
    ]
    return [seqs[i] for i in keep], [phreds[i] for i in keep]


def orient_reads(seqs, phreds, reference):
    """Keep the strand closer to the reference (clonal_code.jl:76-83)."""
    out_seqs, out_phreds = [], []
    for s, p in zip(seqs, phreds):
        rc = reverse_complement(s)
        if align_np.edit_distance(s, reference) > align_np.edit_distance(rc, reference):
            s, p = rc, p[::-1].copy()
        out_seqs.append(s)
        out_phreds.append(p)
    return out_seqs, out_phreds


def trim_primers(seqs, phreds, reference):
    """Cut terminal insert runs of a trim=True alignment to the reference
    (clonal_code.jl:48-63)."""
    scores = Scores.from_error_model(ErrorModel(1e5, 1e-3, 1e-3, 0.0, 0.0))
    out_seqs, out_phreds = [], []
    for s, p in zip(seqs, phreds):
        rs = make_read_scores(s, np.full(len(s), -1.0), 100, scores)
        moves = align_np.align_moves(reference, rs, trim=True)
        x = 0
        while x < len(moves) and moves[x] == align_np.TRACE_INSERT:
            x += 1
        n_end = 0
        while n_end < len(moves) and moves[-1 - n_end] == align_np.TRACE_INSERT:
            n_end += 1
        out_seqs.append(s[x : len(s) - n_end])
        out_phreds.append(p[x : len(s) - n_end])
    return out_seqs, out_phreds


def main():
    rng = np.random.default_rng(11)
    reference, template, _, _, _, _, _, _ = sample_sequences(
        nseqs=1, length=402, error_rate=0.005, rng=rng
    )
    seqs, phreds = make_messy_reads(rng, template, reference)
    print(f"raw reads: {len(seqs)}")

    seqs, phreds = filter_reads(seqs, phreds)
    print(f"after error/length filter: {len(seqs)}")
    seqs, phreds = orient_reads(seqs, phreds, reference)
    seqs, phreds = trim_primers(seqs, phreds, reference)
    lens = [len(s) for s in seqs]
    print(f"after orient+trim: lengths {min(lens)}-{max(lens)} "
          f"(template {len(template)})")

    t0 = time.perf_counter()
    result = rifraf(
        seqs,
        phreds=phreds,
        reference=reference,
        params=RifrafParams(do_score=True),
    )
    dt = time.perf_counter() - t0
    exact = decode_seq(result.consensus) == decode_seq(template)
    print(f"consensus: {len(result.consensus)} bp, == template: {exact}  "
          f"({dt:.1f}s)")
    point = estimate_point_probs(result.error_probs)
    print(f"estimated per-base error: median {np.median(point):.2e}, "
          f"max {point.max():.2e}")
    assert exact, "clonal workflow did not recover the template"


if __name__ == "__main__":
    main()
