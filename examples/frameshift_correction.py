"""Frameshift handling during frame correction.

Reproduces the reference's frameshift docs example
(docs/src/examples.md:70-94): the default parameters penalize
frameshift-causing indels so heavily that a real frameshift in the
template (3,001 bp — not a multiple of three) is "corrected" away,
yielding an in-frame consensus. Re-tuning the reference error model and
the indel-penalty escalation lets the real frameshift survive.

Run:  python examples/frameshift_correction.py        (TPU if visible)
      JAX_PLATFORMS=cpu python examples/frameshift_correction.py
"""

import os
import sys
import time

import numpy as np

# runnable without installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rifraf_tpu import ErrorModel, RifrafParams, Scores, rifraf
from rifraf_tpu.sim.sample import sample_sequences


def main():
    rng = np.random.default_rng(7)
    (reference, template, _, sequences, _, phreds, _, _) = sample_sequences(
        5, 3001, error_rate=0.005, rng=rng
    )
    print(f"template: {len(template)} bp (length % 3 == "
          f"{len(template) % 3}), {len(sequences)} reads")

    t0 = time.perf_counter()
    result = rifraf(sequences, phreds=phreds, reference=reference)
    dt = time.perf_counter() - t0
    in_frame = len(result.consensus) % 3 == 0
    print(f"default params:  len={len(result.consensus)} "
          f"(in frame: {in_frame})  ({dt:.1f}s)")
    assert in_frame, "default penalties should force an in-frame consensus"

    t0 = time.perf_counter()
    result = rifraf(
        sequences,
        phreds=phreds,
        reference=reference,
        params=RifrafParams(
            ref_scores=Scores.from_error_model(ErrorModel(10, 1, 1, 1, 1)),
            ref_indel_mult=1.2,
            max_ref_indel_mults=3,
        ),
    )
    dt = time.perf_counter() - t0
    in_frame = len(result.consensus) % 3 == 0
    print(f"tuned penalties: len={len(result.consensus)} "
          f"(in frame: {in_frame})  ({dt:.1f}s)")
    assert not in_frame, "tuned penalties should keep the real frameshift"


if __name__ == "__main__":
    main()
