"""Consensus on simulated reads, with and without a reference.

Reproduces the reference's first docs example (docs/src/examples.md:11-27):
generate a random 1,200 bp template, a noisy reference, and twenty
simulated reads; run consensus without and then with the reference and
check that both recover the exact template.

Run:  python examples/simulated_consensus.py        (TPU if visible)
      JAX_PLATFORMS=cpu python examples/simulated_consensus.py
"""

import os
import sys
import time

import numpy as np

# runnable without installing the package
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rifraf_tpu import RifrafParams, rifraf
from rifraf_tpu.sim.sample import sample_sequences
from rifraf_tpu.utils.constants import decode_seq


def main():
    rng = np.random.default_rng(3)
    (reference, template, _, sequences, _, phreds, _, _) = sample_sequences(
        20, 1200, rng=rng
    )
    print(f"template: {len(template)} bp, {len(sequences)} reads, "
          f"reference: {len(reference)} bp")

    t0 = time.perf_counter()
    result = rifraf(
        sequences,
        phreds=phreds,
        params=RifrafParams(verbose=1, max_iters=20),
    )
    dt = time.perf_counter() - t0
    ok = decode_seq(result.consensus) == decode_seq(template)
    print(f"without reference: consensus == template: {ok}  ({dt:.1f}s)")
    assert ok, "consensus without reference did not recover the template"

    t0 = time.perf_counter()
    result = rifraf(
        sequences,
        phreds=phreds,
        reference=reference,
        params=RifrafParams(verbose=1, max_iters=20),
    )
    dt = time.perf_counter() - t0
    ok = decode_seq(result.consensus) == decode_seq(template)
    print(f"with reference:    consensus == template: {ok}  ({dt:.1f}s)")
    assert ok, "consensus with reference did not recover the template"


if __name__ == "__main__":
    main()
